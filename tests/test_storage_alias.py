"""Numeric storage-alias probe (VERDICT r4 ask #6; SURVEY §7 hard part
#1 — the reference gets this from Z3 ``Array`` semantics ⚠unv).

A write through a symbolic key ``f(x)`` and a read through a
structurally different but numerically equal key must CONNECT when the
known-bits domain fully determines both values; keys it cannot determine
must keep the sound assumed-distinct behavior (fresh leaf, no false
connection).
"""

import numpy as np

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.ops import u256
from mythril_tpu.symbolic import SymSpec

from test_symbolic import srun


def _entry(sf, lane, key_int):
    """(value_int, val_sym, key_sym) of the storage-cache entry whose
    CONCRETE key equals key_int, or None."""
    b = sf.base
    used = np.asarray(b.st_used[lane])
    keys = np.asarray(b.st_keys[lane])
    vals = np.asarray(b.st_vals[lane])
    vsym = np.asarray(sf.st_val_sym[lane])
    ksym = np.asarray(sf.st_key_sym[lane])
    for k in range(used.shape[0]):
        if used[k] and ksym[k] == 0 and u256.to_int(keys[k]) == key_int:
            return u256.to_int(vals[k]), int(vsym[k]), int(ksym[k])
    return None


def test_provably_equal_keys_connect():
    # storage[x & 0] = 0xAA  (key is a SYMBOLIC node, provably 0)
    # then SLOAD(0) — structurally different, numerically equal — must
    # return 0xAA, proven by storing the loaded word at concrete slot 1
    code = assemble(
        0xAA, 0, "CALLDATALOAD", 0, "AND", "SSTORE",
        0, "SLOAD", 1, "SSTORE", "STOP",
    )
    sf = srun(code, propagate_every=1)
    ent = _entry(sf, 0, 1)
    assert ent is not None, "slot-1 entry missing"
    val, vsym, _ = ent
    assert vsym == 0, "load through aliased key must be the CONCRETE store"
    assert val == 0xAA
    # and the write itself was demoted to a concrete key-0 entry
    ent0 = _entry(sf, 0, 0)
    assert ent0 is not None and ent0[0] == 0xAA


def test_unproven_keys_stay_distinct():
    # storage[x & 1] = 0xAA: the domain knows 255 bits, not bit 0 — the
    # value is NOT provable, so SLOAD(0) must get a fresh leaf (sound
    # assumed-distinct), never the 0xAA
    code = assemble(
        0xAA, 0, "CALLDATALOAD", 1, "AND", "SSTORE",
        0, "SLOAD", 1, "SSTORE", "STOP",
    )
    sf = srun(code, propagate_every=1)
    ent = _entry(sf, 0, 1)
    assert ent is not None
    val, vsym, _ = ent
    assert vsym != 0, "unproven alias must load a fresh symbolic leaf"


def test_probe_gated_on_propagation():
    # with feasibility sweeps disabled the kb domain never materializes;
    # the stale-row guard (key_sym < prop_len) must keep the old
    # assumed-distinct behavior rather than demote on garbage bits
    code = assemble(
        0xAA, 0, "CALLDATALOAD", 0, "AND", "SSTORE",
        0, "SLOAD", 1, "SSTORE", "STOP",
    )
    sf = srun(code, propagate_every=0)
    ent = _entry(sf, 0, 1)
    assert ent is not None
    assert ent[1] != 0  # no sweep -> no proof -> fresh leaf


def test_demoted_miss_leaves_hash_cons_with_concrete():
    # no prior store: SLOAD(x & 0) then SLOAD(0) must hash-cons to the
    # SAME storage leaf (same account, same numeric key), observable as
    # identical val_sym node ids stored at slots 1 and 2
    code = assemble(
        0, "CALLDATALOAD", 0, "AND", "SLOAD", 1, "SSTORE",
        0, "SLOAD", 2, "SSTORE", "STOP",
    )
    sf = srun(code, propagate_every=1)
    e1, e2 = _entry(sf, 0, 1), _entry(sf, 0, 2)
    assert e1 is not None and e2 is not None
    assert e1[1] != 0 and e1[1] == e2[1], \
        "aliased loads must share one hash-consed STORAGE leaf"


def test_rewrite_through_late_proven_key_wins():
    """Ordering hazard (round-5 review): write through f(x) while
    unproven, interleave a concrete write of the aliasing value, then
    RE-write through f(x) — once the proof lands, reads must return the
    chronologically last write (st_seq order), not the highest slot."""
    from mythril_tpu.config import TEST_LIMITS
    from mythril_tpu.symbolic import sym_run

    from test_symbolic import build

    code = assemble(
        0xAA, 0, "CALLDATALOAD", 0, "AND", "SSTORE",  # [f(x)] = AA
        0xBB, 0, "SSTORE",                            # [0]    = BB
        0xCC, 0, "CALLDATALOAD", 0, "AND", "SSTORE",  # [f(x)] = CC (last)
        0, "SLOAD", 1, "SSTORE", "STOP",
    )
    sf, env, corpus = build(code)
    # phase 1: run through all three stores with NO sweeps — the alias
    # stays unproven, so the stores land in separate slots
    sf = sym_run(sf, env, corpus, SymSpec(), TEST_LIMITS,
                 max_steps=15, propagate_every=0)
    # phase 2: sweeps on — the proof lands before the SLOAD
    sf = sym_run(sf, env, corpus, SymSpec(), TEST_LIMITS,
                 max_steps=32, propagate_every=1)
    ent = _entry(sf, 0, 1)
    assert ent is not None
    val, vsym, _ = ent
    assert vsym == 0
    assert val == 0xCC, (
        f"read returned 0x{val:x}: a stale alias-group member shadowed "
        f"the chronologically last write")


def test_berlin_warm_entry_is_not_a_value_hit():
    """Berlin warm-tracking allocates (key, 0, unwritten, seq 0) entries
    on concrete SLOAD misses; a repeated SLOAD of the same unwritten
    slot must keep reading the SAME symbolic STORAGE leaf, never flip to
    concrete 0 via the warm entry (round-5 review finding)."""
    import dataclasses

    import numpy as np

    from mythril_tpu.config import TEST_LIMITS
    from mythril_tpu.core import Corpus, make_env
    from mythril_tpu.disassembler import ContractImage
    from mythril_tpu.symbolic import make_sym_frontier, sym_run

    L = dataclasses.replace(TEST_LIMITS, gas_schedule="berlin")
    code = assemble(5, "SLOAD", 1, "SSTORE",
                    5, "SLOAD", 2, "SSTORE", "STOP")
    img = ContractImage.from_bytecode(code, L.max_code)
    corpus = Corpus.from_images([img])
    active = np.zeros(4, dtype=bool)
    active[0] = True
    sf = make_sym_frontier(4, L, active=active)
    env = make_env(4)
    sf = sym_run(sf, env, corpus, SymSpec(), L, max_steps=64)
    e1, e2 = _entry(sf, 0, 1), _entry(sf, 0, 2)
    assert e1 is not None and e2 is not None
    assert e1[1] != 0, "first load must be a symbolic leaf"
    assert e1[1] == e2[1], (
        "second load of the same unwritten slot flipped away from the "
        "first load's leaf (berlin warm entry matched as a value hit)")


def test_alias_probe_off_compiles_out_to_syntactic_matching():
    """SymSpec(alias_probe=False) is the trace-time opt-out: the same
    program that CONNECTS under the probe must fall back to the sound
    assumed-distinct behavior (fresh leaf), pinning that the compiled-out
    branch stays trace-valid and semantically syntactic."""
    code = assemble(
        0xAA, 0, "CALLDATALOAD", 0, "AND", "SSTORE",
        0, "SLOAD", 1, "SSTORE", "STOP",
    )
    sf = srun(code, spec=SymSpec(alias_probe=False), propagate_every=1)
    ent = _entry(sf, 0, 1)
    assert ent is not None
    assert ent[1] != 0, "probe off: load must be a fresh symbolic leaf"
