"""Supervised engine worker: subprocess isolation, restart backoff,
crash-loop breaker, and campaign/serve wiring (docs/resilience.md
"Process isolation & supervision").

Most tests drive the STUB worker — a real subprocess speaking the real
length-prefixed pickle protocol over real pipes, killed by real
signals, but skipping the engine import — so the supervision machinery
(deadlines, deaths, breaker transitions, exactly-once accounting under
kill+resume) is exercised in milliseconds. One slow test runs the
headline acceptance scenario against the real engine: a SIGSEGV
injected mid-superstep is survived with a byte-identical issue set.
"""

import os
import signal
import time

import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.mythril.campaign import CorpusCampaign
from mythril_tpu.resilience import (BatchTimeout, FaultInjector,
                                    FaultSpec, InjectedKill,
                                    WorkerCrashLoop, WorkerDied,
                                    WorkerSupervisor)


def stub_supervisor(**kw):
    kw.setdefault("stub", True)
    kw.setdefault("batch_timeout", 30.0)
    kw.setdefault("backoff_base", 0.01)
    kw.setdefault("spawn_timeout", 60.0)
    return WorkerSupervisor(**kw)


def kinds(events):
    return [e["kind"] for e in events]


# --- supervisor mechanics -------------------------------------------------

def test_stub_worker_roundtrip_and_rss():
    sup = stub_supervisor()
    try:
        out = sup.run_batch(0, ["a", "b"], [b"\x00", b"\x01"])
        # the reply carries the child's stage attribution (host-phase
        # seconds; device = parent wall - host, computed campaign-side)
        ph = out.pop("phases")
        assert set(ph) == {"device", "host"} and ph["host"] >= 0.0
        assert out == {"issues": [], "paths": 2, "dropped": 0,
                       "iprof": {}}
        st = sup.status()
        assert st["alive"] and st["breaker"] == "closed"
        assert st["rss_bytes"] > 0          # /proc-read gauge source
        assert "worker_spawn" in kinds(sup.events)
    finally:
        sup.close()
    assert not sup.alive()


def test_parent_deadline_kills_hung_worker():
    sup = stub_supervisor(batch_timeout=0.5)
    try:
        with pytest.raises(BatchTimeout):
            sup.run_batch(0, ["__hang__"], [b"\x00"])
        assert not sup.alive()              # the wedged worker is dead
        assert kinds(sup.events).count("worker_death") == 1
        # the next batch respawns and succeeds
        out = sup.run_batch(1, ["a"], [b"\x00"])
        assert out["paths"] == 1
        assert sup.restarts == 1
        assert "worker_restart" in kinds(sup.events)
    finally:
        sup.close()


@pytest.mark.parametrize("mode,signo", [("worker-kill", signal.SIGKILL),
                                        ("worker-segv", signal.SIGSEGV)])
def test_worker_signal_death_and_restart(mode, signo):
    """A real signal into the worker process surfaces as WorkerDied
    with the signal in the exit code, never as parent death."""
    inj = FaultInjector([FaultSpec.parse(f"{mode}:nth=1")])
    sup = stub_supervisor(fault_injector=inj)
    try:
        with pytest.raises(WorkerDied) as ei:
            sup.run_batch(0, ["a"], [b"\x00"])
        assert f"rc={-signo}" in str(ei.value)
        assert inj.log and inj.log[0]["mode"] == mode
        # restart cures it (the spec fired once)
        assert sup.run_batch(0, ["a"], [b"\x00"])["paths"] == 1
    finally:
        sup.close()


def test_breaker_opens_pins_and_closes_after_clean_window():
    """worker-kill:nth=1..3 -> three rapid deaths -> breaker opens
    (WorkerCrashLoop); after the cooldown one half-open probe closes
    it."""
    inj = FaultInjector([FaultSpec.parse("worker-kill:nth=1"),
                         FaultSpec.parse("worker-kill:nth=2"),
                         FaultSpec.parse("worker-kill:nth=3")])
    sup = stub_supervisor(fault_injector=inj, breaker_threshold=3,
                          breaker_window=30.0, breaker_cooldown=0.4)
    try:
        for bi in range(3):
            with pytest.raises(WorkerDied):
                sup.run_batch(bi, ["a"], [b"\x00"])
        assert sup.breaker_state() == "open"
        assert "breaker_open" in kinds(sup.events)
        with pytest.raises(WorkerCrashLoop):
            sup.run_batch(3, ["a"], [b"\x00"])
        time.sleep(0.5)
        assert sup.breaker_state() == "half-open"
        out = sup.run_batch(4, ["a"], [b"\x00"])  # the probe succeeds
        assert out["paths"] == 1
        assert sup.breaker_state() == "closed"
        assert "breaker_close" in kinds(sup.events)
    finally:
        sup.close()


def test_breaker_reopens_when_half_open_probe_dies():
    inj = FaultInjector([FaultSpec.parse(f"worker-kill:nth={k}")
                         for k in (1, 2, 3)])
    sup = stub_supervisor(fault_injector=inj, breaker_threshold=2,
                          breaker_window=30.0, breaker_cooldown=0.2)
    try:
        for bi in range(2):
            with pytest.raises(WorkerDied):
                sup.run_batch(bi, ["a"], [b"\x00"])
        assert sup.breaker_state() == "open"
        time.sleep(0.3)
        with pytest.raises(WorkerDied):   # half-open probe dies (nth=3)
            sup.run_batch(2, ["a"], [b"\x00"])
        assert sup.breaker_state() == "open"   # re-opened, fresh cooldown
        assert kinds(sup.events).count("breaker_open") == 2
    finally:
        sup.close()


# --- campaign wiring ------------------------------------------------------

def make_campaign(contracts, sup, **kw):
    kw.setdefault("batch_size", 2)
    kw.setdefault("lanes_per_contract", 4)
    kw.setdefault("max_steps", 16)
    return CorpusCampaign(contracts, limits=TEST_LIMITS,
                          worker_isolation="on", worker_supervisor=sup,
                          **kw)


STUB_CORPUS = [(f"c{i:03d}", bytes([i])) for i in range(6)]


def test_campaign_worker_path_accounting_and_close():
    sup = stub_supervisor()
    camp = make_campaign(STUB_CORPUS, sup)
    res = camp.run()
    assert res.batches == 3 and res.paths_total == 6
    assert res.batch_status == ["ok", "ok", "ok"]
    assert "worker_spawn" in [e.get("kind") for e in res.backend_events]
    # run() closed the worker: no orphan subprocess outlives the run
    assert camp._supervisor is None and not sup.alive()


def test_campaign_worker_death_replays_through_retry():
    inj = FaultInjector([FaultSpec.parse("worker-kill:nth=2")])
    sup = stub_supervisor(fault_injector=inj)
    camp = make_campaign(STUB_CORPUS, sup, fault_injector=inj)
    res = camp.run()
    # batch 1's dispatch died; the retry replayed it on a fresh worker
    assert res.retries == 1 and not res.quarantined
    assert res.paths_total == 6             # every contract once
    assert res.batch_status == ["ok", "ok-retry", "ok"]
    ks = [e.get("kind") for e in res.backend_events]
    assert ks.count("worker_death") == 1
    assert ks.count("worker_restart") == 1


def test_campaign_breaker_pins_cpu_and_finishes(tmp_path):
    """A crash-looping worker opens the breaker mid-campaign; the
    remaining batches run in-process pinned to CPU — with a stub
    batch_runner standing in for the engine there, injected through
    the supervisor-bypass seam."""
    inj = FaultInjector([FaultSpec.parse(f"worker-kill:nth={k}")
                         for k in (1, 2)])
    sup = stub_supervisor(fault_injector=inj, breaker_threshold=2,
                          breaker_window=30.0, breaker_cooldown=60.0)
    camp = make_campaign(STUB_CORPUS, sup, fault_injector=inj,
                         max_batch_retries=1)
    # the in-process fallback must not need the real engine for this
    # machinery test: swap _exec_batch for a stub AFTER construction
    # (keeping _batch_runner=None so the worker path stays enabled)
    camp._exec_batch = (lambda bi, names, codes, lanes=None, width=None:
                        {"issues": [], "paths": len(names),
                         "dropped": 0, "iprof": {}})
    res = camp.run()
    ks = [e.get("kind") for e in res.backend_events]
    assert ks.count("worker_death") == 2
    assert "breaker_open" in ks
    assert "worker_breaker_pinned" in ks
    assert res.paths_total == 6             # parity: nothing lost/doubled
    assert not res.quarantined
    st = [e for e in res.backend_events
          if e.get("kind") == "worker_breaker_pinned"]
    assert st                               # CPU pin is on the record


def test_campaign_kill_resume_exactly_once_with_worker(tmp_path):
    """InjectedKill (parent-side) mid-campaign with worker isolation:
    the resumed session replays only undurable batches — paths count
    every contract exactly once across both sessions."""
    ck = str(tmp_path / "ck")
    sup = stub_supervisor()
    camp = make_campaign(
        STUB_CORPUS, sup, checkpoint_dir=ck,
        fault_injector=FaultInjector([FaultSpec.parse("kill:batch=1")]))
    with pytest.raises(InjectedKill):
        camp.run()
    assert not sup.alive()  # run()'s finally closed the worker
    sup2 = stub_supervisor()
    res = make_campaign(STUB_CORPUS, sup2, checkpoint_dir=ck).run()
    assert res.batches == 3
    assert res.paths_total == 6             # nothing double-counted


def test_worker_warm_marker_set_and_dropped_on_death():
    inj = FaultInjector([FaultSpec.parse("worker-kill:nth=2")])
    sup = stub_supervisor(fault_injector=inj)
    camp = make_campaign(STUB_CORPUS, sup, fault_injector=inj)
    assert not camp.shape_is_warm()
    res = camp.run()
    assert res.paths_total == 6
    # after batch 0 the shape was worker-warm; the death cleared it;
    # the post-restart batches re-marked it
    assert camp.shape_is_warm()
    deaths = [e for e in res.backend_events
              if e.get("kind") == "worker_death"]
    assert deaths


def test_stub_batch_runner_bypasses_worker():
    """A custom batch_runner has nothing to isolate: no subprocess is
    spawned even with isolation on — fault-machinery tests keep their
    in-process semantics."""
    calls = []

    def runner(bi, names, codes):
        calls.append(bi)
        return {"issues": [], "paths": len(names), "dropped": 0,
                "iprof": {}}

    camp = CorpusCampaign(STUB_CORPUS, batch_size=2,
                          lanes_per_contract=4, limits=TEST_LIMITS,
                          worker_isolation="on", batch_runner=runner)
    res = camp.run()
    assert calls == [0, 1, 2] and res.paths_total == 6
    assert camp._supervisor is None         # never created


def test_worker_isolation_auto_resolution(tmp_path):
    base = dict(batch_size=2, lanes_per_contract=4,
                limits=TEST_LIMITS, max_steps=16)
    off = CorpusCampaign(STUB_CORPUS, worker_isolation="auto", **base)
    assert off.worker_isolation is False
    on = CorpusCampaign(STUB_CORPUS, worker_isolation="auto",
                        fleet_dir=str(tmp_path / "fl"), **base)
    assert on.worker_isolation is True
    with pytest.raises(ValueError):
        CorpusCampaign(STUB_CORPUS, worker_isolation="sometimes", **base)


# --- the headline acceptance scenario (real engine) -----------------------

@pytest.mark.slow
def test_real_engine_segv_mid_superstep_survival(tmp_path):
    """ISSUE 10 acceptance: with worker_isolation=on, a SIGSEGV
    injected into the engine worker mid-superstep is survived by the
    parent — the batch replays through retry, the final issue set is
    byte-identical to an uninjected run, and the restart is counted."""
    from mythril_tpu.disassembler.asm import assemble

    kill = assemble(0, "SELFDESTRUCT")
    safe = assemble(1, 0, "SSTORE", "STOP")
    contracts = [(f"c{i:03d}", kill if i % 2 == 0 else safe)
                 for i in range(4)]

    def mk(**kw):
        return CorpusCampaign(contracts, batch_size=2,
                              lanes_per_contract=8, limits=TEST_LIMITS,
                              max_steps=64, transaction_count=1,
                              modules=["AccidentallyKillable"], **kw)

    ref = mk(worker_isolation="off").run()
    ref_issues = sorted(i["contract"] for i in ref.issues)
    assert ref_issues, "baseline must find issues to assert parity"

    os.environ["MYTHRIL_WORKER_FAULT"] = (
        f"segv:mid-superstep:1:once={tmp_path}/cookie")
    try:
        res = mk(worker_isolation="on").run()
    finally:
        del os.environ["MYTHRIL_WORKER_FAULT"]
    assert sorted(i["contract"] for i in res.issues) == ref_issues
    assert len(res.issues) == len(ref.issues)
    assert not res.quarantined
    ks = [e.get("kind") for e in res.backend_events]
    assert ks.count("worker_death") == 1
    assert ks.count("worker_restart") == 1
