"""Bounded loops + dependency pruner (VERDICT r2 ask #3).

Reference: ``strategy/extensions/bounded_loops.py`` (drop states past
--loop-bound) and ``laser/plugin/plugins/dependency_pruner.py`` (skip
tx-N paths whose read-set no prior tx wrote) — SURVEY.md §5.7 calls these
"the single biggest algorithmic speedup".
"""

import numpy as np

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.core import Corpus, make_env
from mythril_tpu.core.frontier import Trap
from mythril_tpu.disassembler import ContractImage
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.symbolic import SymSpec, make_sym_frontier, sym_run
from mythril_tpu.analysis import SymExecWrapper

L = TEST_LIMITS  # loop_bound=4


def run_one(code, n_lanes=8, max_steps=128, limits=L):
    img = ContractImage.from_bytecode(code, limits.max_code)
    corpus = Corpus.from_images([img])
    active = np.zeros(n_lanes, dtype=bool)
    active[0] = True
    sf = make_sym_frontier(n_lanes, limits, active=active)
    env = make_env(n_lanes)
    return sym_run(sf, env, corpus, SymSpec(), limits, max_steps=max_steps)


def test_infinite_concrete_loop_quiesces_at_bound():
    # for(;;){} — a pure backward JUMP spin must retire at the bound, not
    # burn the whole max_steps budget for the frontier
    code = assemble(("label", "top"), ("ref", "top"), "JUMP")
    out = run_one(code, max_steps=128)
    err = np.asarray(out.base.err_code)
    assert int(err[0]) == Trap.LOOP_BOUND
    # quiesced long before max_steps (bound + small constant)
    assert int(np.asarray(out.base.n_steps)[0]) < 40


def test_symbolic_loop_forks_bounded():
    # while (calldataload(0) != i) i++ — symbolic JUMPI back-edge: each
    # iteration forks an exit path; the spinning lane retires at the bound
    # and the exit paths survive
    code = assemble(
        0,                                  # i
        ("label", "top"),
        "DUP1", 0, "CALLDATALOAD", "EQ", ("ref", "done"), "JUMPI",
        1, "ADD",
        ("ref", "top"), "JUMP",
        ("label", "done"), 1, 0, "SSTORE", "STOP",
    )
    out = run_one(code, n_lanes=16, max_steps=128)
    err = np.asarray(out.base.err_code)
    act = np.asarray(out.base.active)
    halted = np.asarray(out.base.halted)
    assert (err == Trap.LOOP_BOUND).sum() >= 1, "spinner retired"
    assert (act & halted & (err == 0)).sum() >= 2, "exit paths survived"


def test_loop_under_bound_unaffected():
    # a 3-iteration concrete loop (< bound 4) completes normally
    code = assemble(
        3,                                   # counter
        ("label", "top"),
        1, "SWAP1", "SUB",                   # counter -= 1
        "DUP1", ("ref", "top"), "JUMPI",
        1, 0, "SSTORE", "STOP",
    )
    out = run_one(code)
    assert bool(np.asarray(out.base.halted)[0])
    assert int(np.asarray(out.base.err_code)[0]) == 0


def test_dependency_pruner_retires_nonreading_tx2():
    # writes slot 1 every tx, never reads: tx-2 paths read nothing tx-1
    # wrote -> retired at the tx2->tx3 boundary, tx3 never runs
    writer = assemble(42, 1, "SSTORE", "STOP")
    sym = SymExecWrapper([writer], limits=L, lanes_per_contract=4,
                         max_steps=64, transaction_count=3)
    assert len(sym.tx_contexts) == 2, "tx3 had no surviving lanes"
    assert not bool(np.asarray(sym.sf.base.active).any())


def test_dependency_reader_survives_all_txs():
    # counter: slot1 = sload(1) + 1 — tx N reads tx N-1's write, survives
    counter = assemble(0x1, "SLOAD", 1, "ADD", 1, "SSTORE", "STOP")
    sym = SymExecWrapper([counter], limits=L, spec=SymSpec(storage=False),
                         lanes_per_contract=4, max_steps=64,
                         transaction_count=3)
    assert len(sym.tx_contexts) == 3
    assert bool(np.asarray(sym.sf.base.active).any())


def test_dependency_pruner_exempts_first_message_tx_after_creation():
    # code-review r3: with a creation tx the FIRST message call is tx_id 1
    # — it must not be retired for reading nothing the constructor wrote
    ctor = assemble(0, 0, "RETURN")  # empty-effect constructor
    writer = assemble(42, 1, "SSTORE", "STOP")
    sym = SymExecWrapper([writer], creation_bytecodes=[ctor], limits=L,
                         lanes_per_contract=4, max_steps=64,
                         transaction_count=2)
    # creation ctx + first message ctx + second message ctx: the first
    # message tx (writes, reads nothing) must still reach tx 2
    assert len(sym.tx_contexts) == 3
