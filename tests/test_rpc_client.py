"""HTTP JSON-RPC client over a real loopback transport (VERDICT r4 ask
#8; reference: ``tests/rpc_test.py`` mocks its node the same way ⚠unv,
SURVEY.md §4 "RPC tests"). No egress exists in this image, so the "node"
is a threaded ``http.server`` on 127.0.0.1 serving canned JSON-RPC
responses — the full client path (request encoding, transport, retry,
error surfacing) runs for real.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from mythril_tpu.utils.loader import (DynLoader, DynLoaderError,
                                      HttpRpcClient, rpc_client_from_uri)

CODE = "0x6001600201"
SLOT0 = "0x" + "11" * 32


class _Node(BaseHTTPRequestHandler):
    """Canned JSON-RPC node. Class attrs configure behavior per test."""

    fail_first = 0      # 500-error this many requests before answering
    seen = None         # list collecting parsed request payloads
    codes = None        # optional {addr_lower: hexcode} per-address map

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
        cls = type(self)
        if self.path == "/nosuch":
            self.send_error(404, "not found")
            return
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        if cls.seen is not None:
            cls.seen.append(body)
        if cls.fail_first > 0:
            cls.fail_first -= 1
            self.send_error(500, "flaky node")
            return
        method, params = body["method"], body["params"]
        if method == "eth_getCode":
            result = (cls.codes.get(params[0].lower(), "0x")
                      if cls.codes is not None else CODE)
        elif method == "eth_getStorageAt":
            result = SLOT0 if int(params[1], 16) == 0 else "0x0"
        elif method == "eth_getBalance":
            result = "0xde0b6b3a7640000"  # 1 ether
        elif method == "eth_blockNumber":
            result = "0x10"
        elif method == "eth_getTransactionCount":
            result = "0x2"
        else:
            out = {"jsonrpc": "2.0", "id": body["id"],
                   "error": {"code": -32601, "message": "method not found"}}
            self._reply(out)
            return
        self._reply({"jsonrpc": "2.0", "id": body["id"], "result": result})

    def _reply(self, obj):
        data = json.dumps(obj).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):  # silence per-request stderr noise
        pass


@pytest.fixture()
def node():
    _Node.fail_first = 0
    _Node.seen = []
    _Node.codes = None
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Node)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}"
    finally:
        srv.shutdown()
        srv.server_close()


def test_get_code_and_storage(node):
    c = HttpRpcClient(node)
    assert c.eth_getCode("0x" + "ab" * 20) == CODE
    assert c.eth_getStorageAt("0x" + "ab" * 20, "0x0") == SLOT0
    assert c.eth_getStorageAt("0x" + "ab" * 20, "0x5") == "0x0"
    # request encoding: jsonrpc 2.0, monotonically increasing ids
    assert all(r["jsonrpc"] == "2.0" for r in _Node.seen)
    ids = [r["id"] for r in _Node.seen]
    assert ids == sorted(ids)


def test_eth_json_rpc_surface(node):
    c = HttpRpcClient(node)
    assert int(c.eth_getBalance("0x" + "ab" * 20), 16) == 10**18
    assert int(c.eth_blockNumber(), 16) == 16
    assert int(c.eth_getTransactionCount("0x" + "ab" * 20), 16) == 2


def test_transport_retry_then_success(node):
    _Node.fail_first = 2
    c = HttpRpcClient(node, retries=2)
    assert c.eth_getCode("0x" + "ab" * 20) == CODE  # 2 failures absorbed


def test_transport_retries_exhausted(node):
    _Node.fail_first = 10
    c = HttpRpcClient(node, retries=1)
    # 5xx is retried; once exhausted the HTTP status surfaces (an
    # answered request is never reported as a transport fault)
    with pytest.raises(DynLoaderError, match="rpc http 500"):
        c.eth_getCode("0x" + "ab" * 20)


def test_http_4xx_not_retried(node):
    c = HttpRpcClient(node + "/nosuch", retries=3)
    with pytest.raises(DynLoaderError, match="rpc http 404"):
        c.eth_getCode("0x" + "ab" * 20)


def test_rpc_error_not_retried(node):
    c = HttpRpcClient(node, retries=3)
    with pytest.raises(DynLoaderError, match="method not found"):
        c._call("eth_bogus", [])
    # one request only: JSON-RPC errors are answers, not transport faults
    assert len(_Node.seen) == 1


def test_dead_endpoint_fails_clearly():
    c = HttpRpcClient("http://127.0.0.1:1", timeout=0.2, retries=0)
    with pytest.raises(DynLoaderError, match="transport"):
        c.eth_getCode("0x" + "ab" * 20)


def test_dynloader_over_http(node):
    dl = DynLoader(rpc_client_from_uri(node))
    addr = int("ab" * 20, 16)
    assert dl.dynld(addr) == bytes.fromhex(CODE[2:])
    assert dl.read_storage(addr, 0) == int(SLOT0, 16)
    assert dl.read_balance(addr) == 10**18


def test_read_storage_cli_end_to_end(node, capsys):
    # `read-storage --rpc http://...` drives the real client (VERDICT r4
    # ask #8 done-criterion)
    from mythril_tpu.interfaces.cli import main

    rc = main(["read-storage", "0x0", "0x" + "ab" * 20, "--rpc", node])
    out = capsys.readouterr().out.strip()
    assert rc == 0
    assert out == "0x" + "11" * 32


def test_analyze_address_over_http(node, capsys):
    from mythril_tpu.interfaces.cli import main

    rc = main(["analyze", "-a", "0x" + "ab" * 20, "--rpc", node,
               "-t", "1", "--max-steps", "16", "--lanes-per-contract", "4",
               "--limits-profile", "test", "-o", "json"])
    out = capsys.readouterr().out
    assert rc == 0
    assert json.loads(out)["success"] is True


def test_prefetch_callees_scans_push20():
    from mythril_tpu.disassembler.asm import assemble

    callee_addr = int("cd" * 20, 16)
    target = assemble(
        0, 0, 0, 0, 0, ("push20", callee_addr), ("push2", 50000),
        "CALL", "POP", "STOP",
    )
    callee = assemble(5, 9, "SSTORE", "STOP")

    class MockClient:
        def eth_getCode(self, address):
            if int(address, 16) == callee_addr:
                return "0x" + callee.hex()
            return "0x"

        def eth_getStorageAt(self, address, slot):
            return "0x0"

    dl = DynLoader(MockClient())
    got = dl.prefetch_callees(target)
    assert got == [(callee_addr, callee)]


def test_analyze_address_prefetches_callees(node, capsys, tmp_path):
    """analyze -a pulls the target AND its hardcoded callee; the callee
    joins the corpus under its REAL address, observable in the
    statespace dump's per-contract instruction coverage."""
    from mythril_tpu.disassembler.asm import assemble
    from mythril_tpu.interfaces.cli import main

    callee_addr = int("cd" * 20, 16)
    target = assemble(
        0, 0, 0, 0, 0, ("push20", callee_addr), ("push2", 50000),
        "CALL", "POP", "STOP",
    )
    callee = assemble(5, 9, "SSTORE", "STOP")
    _Node.codes = {"0x" + "ab" * 20: "0x" + target.hex(),
                   "0x" + "cd" * 20: "0x" + callee.hex()}
    ss = tmp_path / "ss.json"
    rc = main(["analyze", "-a", "0x" + "ab" * 20, "--rpc", node,
               "-t", "1", "--max-steps", "32", "--lanes-per-contract", "4",
               "--limits-profile", "test", "--statespace-json", str(ss),
               "-o", "json"])
    err = capsys.readouterr().err
    assert rc == 0
    assert "dynld: loaded callee 0x" + "cd" * 20 in err
    cov = json.loads(ss.read_text())["instruction_coverage_pct"]
    assert len(cov) == 2  # target + prefetched callee both in the corpus


def test_prefetch_excludes_target_and_bounds_attempts():
    from mythril_tpu.disassembler.asm import assemble

    self_addr = int("ab" * 20, 16)
    callee_addr = int("cd" * 20, 16)
    # self-reference + callee + a pile of garbage address constants
    toks = [("push20", self_addr), "POP", ("push20", callee_addr), "POP"]
    for k in range(40):
        toks += [("push20", 0x1000 + k), "POP"]
    target = assemble(*toks, "STOP")
    callee = assemble("STOP")
    probes = []

    class MockClient:
        def eth_getCode(self, address):
            probes.append(address)
            return "0x" + callee.hex() if int(address, 16) == callee_addr \
                else "0x"

        def eth_getStorageAt(self, address, slot):
            return "0x0"

    dl = DynLoader(MockClient())
    got = dl.prefetch_callees(target, limit=2, exclude=(self_addr,))
    assert got == [(callee_addr, callee)]       # self-ref never fetched
    assert all(int(a, 16) != self_addr for a in probes)
    assert len(probes) <= 8                      # 4×limit round-trip bound
