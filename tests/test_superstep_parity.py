"""Byte-parity for the superstep restructure (ROADMAP scaling cliff).

The packed fork map (scatter-free rank/sort in ``expand_forks``), the
narrowed pop_frames cond boundary, and the unrolled while-loop body are
PERFORMANCE restructures: every one of them must leave the analysis
OUTPUT bit-identical to the legacy per-step path, or a future perf PR
could trade correctness for throughput without any test noticing.

Tier-1 runs the full pipeline (SymExecWrapper → fire_lasers) over the
synthetic soak mix twice — legacy/per-step vs packed/unrolled — and
requires identical issue rows, identical surviving paths, and identical
iprof rows. The per-fork-policy engine-level matrix is ``slow`` (each
(policy, impl, unroll) combination is a fresh XLA compile of the whole
engine — minutes of compile for seconds of run).
"""

import os
import sys

import numpy as np
import jax
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import gen_corpus  # noqa: E402  (tools/ is not a package)

from mythril_tpu.analysis import SymExecWrapper, fire_lasers  # noqa: E402
from mythril_tpu.config import DEFAULT_LIMITS  # noqa: E402
from mythril_tpu.core import Corpus, make_env  # noqa: E402
from mythril_tpu.disassembler import ContractImage  # noqa: E402
from mythril_tpu.disassembler.asm import erc20_like  # noqa: E402
from mythril_tpu.symbolic import SymSpec, make_sym_frontier  # noqa: E402
from mythril_tpu.symbolic.engine import sym_run  # noqa: E402

L = DEFAULT_LIMITS

# a vulnerable/safe pair per class keeps the run cheap while still
# exercising forks, storage, reverts and the issue pipeline
_SOAK_N = 4


def _soak_codes():
    return [gen_corpus.MIX[k % len(gen_corpus.MIX)](k)
            for k in range(_SOAK_N)]


def _pipeline(fork_impl, unroll):
    sym = SymExecWrapper(_soak_codes(), lanes_per_contract=4,
                         max_steps=48, transaction_count=1,
                         enable_iprof=True,
                         fork_impl=fork_impl, unroll=unroll)
    report = fire_lasers(sym)
    return sym, report


def _tree_mismatches(a, b):
    la, _ = jax.tree_util.tree_flatten_with_path(a)
    lb, _ = jax.tree_util.tree_flatten_with_path(b)
    assert len(la) == len(lb)
    bad = []
    for (pa, xa), (_, xb) in zip(la, lb):
        if xa is None and xb is None:
            continue
        if not np.array_equal(np.asarray(xa), np.asarray(xb)):
            bad.append(jax.tree_util.keystr(pa))
    return bad


def _assert_pipeline_parity(sym_a, rep_a, sym_b, rep_b):

    issues_a = [i.as_dict() for i in rep_a.sorted()]
    issues_b = [i.as_dict() for i in rep_b.sorted()]
    assert issues_a == issues_b, (
        "issue rows diverged between legacy/per-step and packed/unrolled")

    # surviving paths: same frontier, lane for lane
    bad = _tree_mismatches(sym_a.sf, sym_b.sf)
    assert not bad, f"final frontier diverged on leaves: {bad[:8]}"
    assert sym_a.coverage == sym_b.coverage

    # iprof rows: identical opcode -> count table
    assert sym_a.iprof == sym_b.iprof


def test_pipeline_parity_packed_unrolled_vs_legacy():
    # unroll=2 keeps the XLA compile of the unrolled body inside the
    # tier-1 wall; the deeper unroll=4 body is covered by the slow test
    sym_a, rep_a = _pipeline("legacy", 1)
    sym_b, rep_b = _pipeline("packed", 2)
    _assert_pipeline_parity(sym_a, rep_a, sym_b, rep_b)


@pytest.mark.slow
def test_pipeline_parity_deep_unroll():
    sym_a, rep_a = _pipeline("legacy", 1)
    sym_b, rep_b = _pipeline("packed", 4)
    _assert_pipeline_parity(sym_a, rep_a, sym_b, rep_b)


def _run_engine(policy, impl, unroll, defer=True, cov=False):
    P = 32
    img = ContractImage.from_bytecode(erc20_like(), L.max_code)
    corpus = Corpus.from_images([img])
    active = np.zeros(P, dtype=bool)
    active[: P // 4] = True
    sf = make_sym_frontier(P, L, active=active)
    env = make_env(P)
    return sym_run(sf, env, corpus, SymSpec(), L, max_steps=24,
                   fork_policy=policy, defer_starved=defer,
                   track_coverage=cov, fork_impl=impl, unroll=unroll)


@pytest.mark.slow
@pytest.mark.parametrize("policy", ["fifo", "shallow", "deep", "weighted",
                                    "random", "beam", "coverage"])
def test_sym_run_parity_per_policy(policy):
    cov = policy == "coverage"
    a = _run_engine(policy, "legacy", 1, cov=cov)
    b = _run_engine(policy, "packed", 2, cov=cov)
    bad = _tree_mismatches(a, b)
    assert not bad, f"{policy}: frontier diverged on leaves: {bad[:8]}"
