"""Real-world-shaped smoke corpus (VERDICT r4 ask #9).

This image has zero network egress, so genuine Etherscan bytecode cannot
be vendored. What CAN be, faithfully:

- **EIP-1167 minimal proxy** — the exact spec byte sequence every real
  clone deployment uses (only the embedded implementation address varies
  per deployment; here it's the in-corpus ERC-20 so the
  DELEGATECALL resolves in-batch).
- **Pre-0.8-Solidity-shaped contracts** assembled at real scale: a full
  ERC-20 (transfer/transferFrom/approve/allowance/balanceOf/totalSupply/
  decimals, canonical keccak event topics, nested-mapping allowance
  slots), an ERC-721 (ownerOf/mint/approve/transferFrom with auth
  checks), and a 2-of-3 multisig (owner set, confirmation bitmap,
  value-bearing execute). Structure mirrors solc output: selector
  dispatcher, keccak mapping keys, LOG3 events with the canonical
  topics, revert-on-failure guards.

The canonical topics are the real ones (keccak of the event
signatures): Transfer(address,address,uint256) =
0xddf252ad..., Approval(address,address,uint256) = 0x8c5be1e5....
"""

from mythril_tpu.core.frontier import contract_address
from mythril_tpu.disassembler.asm import (assemble, mapping_key,
                                          selector_prologue)

TRANSFER_TOPIC = 0xDDF252AD1BE2C89B69C2B068FC378DAA952BA7F163C4A11628F55A4DF523B3EF
APPROVAL_TOPIC = 0x8C5BE1E5EBEC7D5BD14F71427D1E84F3DD0314C0F7B2291E5B200AC8C7C3B925


def eip1167_proxy(impl: int) -> bytes:
    """EIP-1167 minimal proxy runtime, exact spec bytes around the
    20-byte implementation address."""
    return (bytes.fromhex("363d3d373d3d3d363d73")
            + impl.to_bytes(20, "big")
            + bytes.fromhex("5af43d82803e903d91602b57fd5bf3"))


_mapkey = mapping_key  # shared slot convention (disassembler/asm.py)


def _revert():
    return [0, 0, "REVERT"]


def _ret_true():
    return [1, 0, "MSTORE", 32, 0, "RETURN"]


def _log3(topic0: int):
    """LOG3(mem[0:32], topic0, t1, t2) with t1/t2 already on stack as
    [.., t1, t2]; data word must be at memory 0."""
    # LOG3 pops off, len, t0, t1, t2 — push reversed
    return [("push32", topic0), 32, 0, "LOG3"]


def erc20_full() -> bytes:
    """Pre-0.8-style token: unchecked add on credit (the classic real-
    world SWC-101 shape), canonical events, nested allowance mapping
    allowance[owner][spender] = keccak(spender . keccak(owner . 1))."""
    return assemble(
        *selector_prologue(),
        "DUP1", 0xA9059CBB, "EQ", ("ref", "transfer"), "JUMPI",
        "DUP1", 0x23B872DD, "EQ", ("ref", "transferFrom"), "JUMPI",
        "DUP1", 0x095EA7B3, "EQ", ("ref", "approve"), "JUMPI",
        "DUP1", 0x70A08231, "EQ", ("ref", "balanceOf"), "JUMPI",
        "DUP1", 0xDD62ED3E, "EQ", ("ref", "allowance"), "JUMPI",
        "DUP1", 0x18160DDD, "EQ", ("ref", "totalSupply"), "JUMPI",
        "DUP1", 0x313CE567, "EQ", ("ref", "decimals"), "JUMPI",
        *_revert(),

        # -- transfer(to, amount): caller pays --
        ("label", "transfer"), "POP",
        4, "CALLDATALOAD", 36, "CALLDATALOAD",   # [to, amt]
        "CALLER", ("ref", "xfer"), "JUMP",       # [to, amt, from] -> common

        # -- transferFrom(from, to, amount): spend allowance first --
        ("label", "transferFrom"), "POP",
        36, "CALLDATALOAD", 68, "CALLDATALOAD",  # [to, amt]
        4, "CALLDATALOAD",                       # [to, amt, from]
        # allowance key = keccak(caller . keccak(from . 1))
        "DUP1", *_mapkey(1),                     # [to, amt, from, k1]
        "CALLER", *_mapkey_dyn(),                # [to, amt, from, akey]
        "DUP1", "SLOAD",                         # [to, amt, from, akey, al]
        "DUP4", "DUP2", "LT", ("ref", "nope"), "JUMPI",  # al < amt -> revert
        "DUP4", "SWAP1", "SUB",                  # [to, amt, from, akey, al-amt]
        "SWAP1", "SSTORE",                       # [to, amt, from]
        ("ref", "xfer"), "JUMP",

        # -- common transfer body: [to, amt, from] --
        ("label", "xfer"),
        "DUP1", *_mapkey(0),                     # [to, amt, from, fkey]
        "DUP1", "SLOAD",                         # [to, amt, from, fkey, fbal]
        "DUP4", "DUP2", "LT", ("ref", "nope"), "JUMPI",
        "DUP4", "SWAP1", "SUB", "SWAP1", "SSTORE",  # balances[from] -= amt; [to, amt, from]
        "DUP3", *_mapkey(0),                     # [to, amt, from, tkey]
        "DUP1", "SLOAD",                         # [.., tkey, tbal]
        "DUP4", "ADD",                           # unchecked credit (pre-0.8)
        "SWAP1", "SSTORE",                       # [to, amt, from]
        # Transfer(from, to, amt): data word = amt, topics t2=from t3=to
        # (LOG3 pops off, len, t1, then t2 from the stack TOP — so the
        # stack must be [to, from] with `from` on top)
        "DUP2", 0, "MSTORE",                     # mem[0]=amt; [to, amt, from]
        "SWAP1", "POP",                          # [to, from]
        *_log3(TRANSFER_TOPIC),
        *_ret_true(),
        ("label", "nope"), *_revert(),

        # -- approve(spender, amount) --
        ("label", "approve"), "POP",
        36, "CALLDATALOAD",                      # [amt]
        "CALLER", *_mapkey(1),                   # [amt, k1=keccak(caller.1)]
        4, "CALLDATALOAD", *_mapkey_dyn(),       # [amt, akey]
        "DUP2", "SWAP1", "SSTORE",               # allowance[caller][sp]=amt; [amt]
        0, "MSTORE",                             # mem[0]=amt
        4, "CALLDATALOAD", "CALLER",             # [spender, caller]: t2=owner t3=spender
        *_log3(APPROVAL_TOPIC),
        *_ret_true(),

        # -- views --
        ("label", "balanceOf"), "POP",
        4, "CALLDATALOAD", *_mapkey(0), "SLOAD",
        0, "MSTORE", 32, 0, "RETURN",
        ("label", "allowance"), "POP",
        4, "CALLDATALOAD", *_mapkey(1),
        36, "CALLDATALOAD", *_mapkey_dyn(), "SLOAD",
        0, "MSTORE", 32, 0, "RETURN",
        ("label", "totalSupply"), "POP",
        2, "SLOAD", 0, "MSTORE", 32, 0, "RETURN",
        ("label", "decimals"), "POP",
        18, 0, "MSTORE", 32, 0, "RETURN",
    )


def _mapkey_dyn():
    """[.., slotword, key] -> keccak(key . slotword) — nested-mapping
    second hop where the 'slot' is itself a computed keccak."""
    return ["SWAP1", 32, "MSTORE", 0, "MSTORE", 64, 0, "SHA3"]


def erc721_like() -> bytes:
    """owners[tokenId] @ keccak(id.0), approvals @ keccak(id.1),
    contract owner @ slot 2 (set by constructor)."""
    return assemble(
        *selector_prologue(),
        "DUP1", 0x6352211E, "EQ", ("ref", "ownerOf"), "JUMPI",
        "DUP1", 0x40C10F19, "EQ", ("ref", "mint"), "JUMPI",
        "DUP1", 0x095EA7B3, "EQ", ("ref", "approve"), "JUMPI",
        "DUP1", 0x23B872DD, "EQ", ("ref", "transferFrom"), "JUMPI",
        *_revert(),

        ("label", "ownerOf"), "POP",
        4, "CALLDATALOAD", *_mapkey(0), "SLOAD",
        "DUP1", "ISZERO", ("ref", "nope"), "JUMPI",   # nonexistent -> revert
        0, "MSTORE", 32, 0, "RETURN",

        # mint(to, id): onlyOwner, must not exist
        ("label", "mint"), "POP",
        "CALLER", 2, "SLOAD", "EQ", "ISZERO", ("ref", "nope"), "JUMPI",
        36, "CALLDATALOAD", "DUP1", *_mapkey(0),      # [id, okey]
        "DUP1", "SLOAD", "ISZERO", "ISZERO", ("ref", "nope"), "JUMPI",
        4, "CALLDATALOAD", "SWAP1", "SSTORE",         # owners[id]=to; [id]
        0, "MSTORE",                                   # mem[0]=id (event data)
        4, "CALLDATALOAD", 0,                          # [to, 0]: t2=from=0 t3=to
        *_log3(TRANSFER_TOPIC),
        *_ret_true(),

        # approve(to, id): only current owner
        ("label", "approve"), "POP",
        36, "CALLDATALOAD", "DUP1", *_mapkey(0), "SLOAD",  # [id, owner]
        "DUP1", "CALLER", "EQ", "ISZERO", ("ref", "nope"), "JUMPI",
        "POP",                                         # [id]
        "DUP1", *_mapkey(1),                           # [id, akey]
        4, "CALLDATALOAD", "SWAP1", "SSTORE",          # approvals[id]=to; [id]
        0, "MSTORE",
        4, "CALLDATALOAD", "CALLER",                   # t2=owner t3=approved
        *_log3(APPROVAL_TOPIC),
        *_ret_true(),

        # transferFrom(from, to, id): caller is owner or approved
        ("label", "transferFrom"), "POP",
        68, "CALLDATALOAD",                            # [id]
        "DUP1", *_mapkey(0), "DUP1", "SLOAD",          # [id, okey, owner]
        "DUP1", 4, "CALLDATALOAD", "EQ", "ISZERO", ("ref", "nope"), "JUMPI",
        "CALLER", "EQ",                                # owner == caller ?
        ("ref", "auth_ok"), "JUMPI",
        # else need approvals[id] == caller
        "DUP2", *_mapkey(1), "SLOAD", "CALLER", "EQ", "ISZERO",
        ("ref", "nope"), "JUMPI",
        ("label", "auth_ok"),
        36, "CALLDATALOAD", "SWAP1", "SSTORE",         # owners[id]=to; [id]
        "DUP1", *_mapkey(1), 0, "SWAP1", "SSTORE",     # approvals[id]=0; [id]
        0, "MSTORE",
        36, "CALLDATALOAD", 4, "CALLDATALOAD",         # [to, from]: t2=from t3=to
        *_log3(TRANSFER_TOPIC),
        *_ret_true(),
        ("label", "nope"), *_revert(),
    )


def multisig_2of3() -> bytes:
    """Owners at slots 0-2; pending tx (to@10, value@11, confirm
    bitmap@12); execute fires on the 2nd confirmation with a real
    value-bearing CALL — the realistic multi-send/depth shape."""
    def owner_index():
        # [..] -> [idx] (0,1,2) or revert; also leaves nothing else
        return [
            "CALLER", 0, "SLOAD", "EQ", ("ref", "own0"), "JUMPI",
            "CALLER", 1, "SLOAD", "EQ", ("ref", "own1"), "JUMPI",
            "CALLER", 2, "SLOAD", "EQ", ("ref", "own2"), "JUMPI",
            *_revert(),
        ]

    return assemble(
        *selector_prologue(),
        "DUP1", 0xC6427474, "EQ", ("ref", "submit"), "JUMPI",
        "DUP1", 0xC01A8C84, "EQ", ("ref", "confirm"), "JUMPI",
        "DUP1", 0x784547A7, "EQ", ("ref", "isConfirmed"), "JUMPI",
        *_revert(),

        # submit(to, value): any owner; resets bitmap to caller's bit
        ("label", "submit"), "POP",
        *owner_index(),
        ("label", "own0"), 1, ("ref", "subgo"), "JUMP",
        ("label", "own1"), 2, ("ref", "subgo"), "JUMP",
        ("label", "own2"), 4,
        ("label", "subgo"),                         # [bit]
        4, "CALLDATALOAD", 10, "SSTORE",            # to
        36, "CALLDATALOAD", 11, "SSTORE",           # value
        12, "SSTORE",                               # bitmap = caller's bit
        *_ret_true(),

        # confirm(): set bit; if two distinct bits -> execute
        ("label", "confirm"), "POP",
        *_confirm_tail(),

        ("label", "isConfirmed"), "POP",
        12, "SLOAD", 0, "MSTORE", 32, 0, "RETURN",
    )


def _confirm_tail():
    return [
        "CALLER", 0, "SLOAD", "EQ", ("ref", "c0"), "JUMPI",
        "CALLER", 1, "SLOAD", "EQ", ("ref", "c1"), "JUMPI",
        "CALLER", 2, "SLOAD", "EQ", ("ref", "c2"), "JUMPI",
        *_revert(),
        ("label", "c0"), 1, ("ref", "cgo"), "JUMP",
        ("label", "c1"), 2, ("ref", "cgo"), "JUMP",
        ("label", "c2"), 4,
        ("label", "cgo"),                            # [bit]
        12, "SLOAD", "OR", "DUP1", 12, "SSTORE",     # bitmap |= bit; [bm]
        # popcount(bm) >= 2 over 3 bits: bm in {3,5,6,7}
        "DUP1", 3, "EQ",
        "DUP2", 5, "EQ", "OR",
        "DUP2", 6, "EQ", "OR",
        "DUP2", 7, "EQ", "OR",
        "ISZERO", ("ref", "pend"), "JUMPI",
        # execute: CALL(to=slot10, value=slot11), clear state
        0, 0, 0, 0,
        11, "SLOAD", 10, "SLOAD", ("push3", 100000), "CALL",
        "POP",
        0, 12, "SSTORE", 0, 11, "SSTORE", 0, 10, "SSTORE",
        ("label", "pend"), "POP", *_ret_true(),
    ]


def build_realworld():
    """[(name, runtime)] — the smoke corpus. Proxy delegates to the
    ERC-20 at corpus index 1 (pair the two in that order)."""
    return [
        ("Eip1167Proxy", eip1167_proxy(contract_address(1))),
        ("Erc20Full", erc20_full()),
        ("Erc721", erc721_like()),
        ("Multisig2of3", multisig_2of3()),
    ]
