"""Light CLI paths must not initialize a JAX backend (round-5 invariant).

``campaign-merge`` / ``function-to-hash`` / ``version`` are pure host
work; a module-level jnp array anywhere in their import chains commits
to a device at import time, which on a wedged TPU runtime hangs the
process before ``main()`` runs (the round-5 ``u256._MASK32`` bug —
docs/tpu-wedge-round5.md). Locked in by asserting, in a clean
subprocess, that the chains import with ``xla_bridge._backends`` still
empty.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = """
import sys
sys.path.insert(0, {repo!r})
{body}
from jax._src import xla_bridge
assert not xla_bridge._backends, (
    "backend initialized by a light import: %r" % (xla_bridge._backends,))
print("CLEAN")
"""


def _assert_clean(body: str):
    # a clean env (no JAX_PLATFORMS pin): the invariant is that the
    # import itself never ASKS for a backend, whatever the platform
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    r = subprocess.run(
        [sys.executable, "-c", _PROBE.format(repo=REPO, body=body)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert r.returncode == 0 and "CLEAN" in r.stdout, (
        f"light import touched a backend:\n{r.stdout}\n{r.stderr[-2000:]}")


def test_campaign_merge_chain_is_backend_free():
    _assert_clean(
        "from mythril_tpu.mythril.campaign import merge_campaigns\n"
        "assert merge_campaigns([{'contracts': 1}])['contracts'] == 1")


def test_signature_keccak_chain_is_backend_free():
    _assert_clean(
        "from mythril_tpu.utils.signatures import selector_of\n"
        "assert selector_of('transfer(address,uint256)') == 'a9059cbb'")


def test_cli_parser_and_version_are_backend_free():
    _assert_clean(
        "from mythril_tpu.interfaces.cli import create_parser\n"
        "create_parser().parse_args(['version'])")
