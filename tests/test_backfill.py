"""Whole-chain backfill (serve/backfill.py, ``serve --backfill URI``):
backward window walk to genesis, durable two-ended cursor, kill/resume
exactly-once (dedupe makes the at-most-one-window overlap free), and
bounded backoff with jitter on RPC failure. Reuses the canned loopback
JSON-RPC chain + stub engine from tests/test_follower.py.
"""

import json
import os
import time

import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.obs import metrics as obs_metrics
from mythril_tpu.serve import (BACKFILL_PRIORITY, FOLLOWER_PRIORITY,
                               AnalysisDaemon, ServeOptions)
from test_follower import (StubCampaign, _ChainNode, _deploy, _wait,
                           counter, node)  # noqa: F401

CFH_DONT_CARE = None  # backfill uses the daemon's base config

ADDRS = ["0x" + f"{i:02x}" * 20 for i in range(1, 9)]


@pytest.fixture(autouse=True)
def _restore_registry_enabled():
    was = obs_metrics.REGISTRY.enabled
    yield
    obs_metrics.REGISTRY.enabled = was


def _daemon(tmp_path, node_url, stub, **kw):
    kw.setdefault("options", ServeOptions(batch_size=4))
    kw.setdefault("solver_store", None)
    kw.setdefault("backfill_window", 2)
    dm = AnalysisDaemon(
        data_dir=str(tmp_path / "serve_data"), port=0,
        campaign_factory=(lambda cfg: stub),
        backfill_uri=node_url, backfill_poll=0.05, **kw)
    dm.backfill_poll = 0.05
    dm.start()
    dm.backfill.poll = 0.05
    dm.backfill.idle_poll = 0.05
    return dm


def test_backfill_walks_history_to_genesis(tmp_path, node):
    """The walker anchors hi at the head, walks backward in windows,
    analyzes every historical deployment, and finishes at lo == 0 with
    the cursor durable and the verdicts stored."""
    _ChainNode.head = 5
    _deploy(1, ADDRS[0], "0x01aa")         # distinct bytecodes so the
    _deploy(3, ADDRS[1], "0x02bb")         # store gets distinct keys
    _deploy(4, ADDRS[2], "0x03cc")
    stub = StubCampaign()
    dm = _daemon(tmp_path, node, stub)
    try:
        bf = dm.backfill
        assert bf is not None and bf.priority == BACKFILL_PRIORITY
        assert BACKFILL_PRIORITY < FOLLOWER_PRIORITY
        assert _wait(lambda: bf.status()["done"]), bf.status()
        st = bf.status()
        assert st["lo"] == 0 and st["hi"] == 5
        assert st["remaining_blocks"] == 0
        assert st["ingested"] == 3
        # all three historical contracts analyzed and stored
        assert _wait(lambda: dm.store.count() == 3)
        names = [n for b in stub.batches for n in b]
        assert {n.split("@")[0].split("_")[0][:42] for n in names} \
            >= {a for a in ADDRS[:3]}
        # durable cursor on disk
        cur = json.load(open(os.path.join(dm.data_dir,
                                          "backfill_cursor.json")))
        assert cur["lo"] == 0 and cur["hi"] == 5
        # healthz carries the backfill block
        health = dm.health()
        assert health["backfill"]["done"] is True
        assert health["tenants"]["backfill"]["admitted"] == 3
    finally:
        dm.scheduler.abort()
        dm.shutdown("test teardown")


def test_backfill_kill_resume_exactly_once(tmp_path, node):
    """Stop the daemon mid-walk; the restarted walker resumes from the
    durable cursor (re-scanning at most one window) and every contract
    in the whole range ends up analyzed-or-deduped exactly once —
    the store holds exactly one verdict per distinct bytecode and no
    bytecode was ANALYZED twice."""
    _ChainNode.head = 7
    for i, a in enumerate(ADDRS[:6]):
        _deploy(i + 1, a, f"0x0{(i % 3) + 1}{'ee' * 4}")
    stub1 = StubCampaign()
    dm1 = _daemon(tmp_path, node, stub1)
    try:
        bf1 = dm1.backfill
        # let it commit at least one window, then kill mid-walk
        assert _wait(lambda: bf1.windows >= 1 and bf1.lo < 8)
    finally:
        dm1.scheduler.abort()
        dm1.shutdown("mid-walk kill")
    lo_after_kill = json.load(open(os.path.join(
        dm1.data_dir, "backfill_cursor.json")))["lo"]
    assert 0 <= lo_after_kill < 8
    analyzed_before = [n for b in stub1.batches for n in b]

    stub2 = StubCampaign()
    dm2 = _daemon(tmp_path, node, stub2)
    try:
        bf2 = dm2.backfill
        assert bf2.hi == 7                       # anchored once, durable
        assert bf2.lo == lo_after_kill           # resumed, not re-anchored
        assert _wait(lambda: bf2.status()["done"]), bf2.status()
        # exactly-once: one verdict per distinct bytecode (3), and the
        # second run never re-analyzed a bytecode the first run
        # committed (the overlap window resolves via dedupe)
        assert _wait(lambda: dm2.store.count() == 3)
        analyzed_after = [n for b in stub2.batches for n in b]
        assert len(analyzed_before) + len(analyzed_after) <= 6
        # merged ingest record covers every deployment in the range
        assert bf1.ingested + bf2.ingested >= 6
    finally:
        dm2.scheduler.abort()
        dm2.shutdown("test teardown")


def test_backfill_rpc_failure_backoff_with_jitter_then_recovery(
        tmp_path, node):
    _ChainNode.head = 3
    _deploy(1, ADDRS[0], "0x01aa")
    _ChainNode.fail_all = True
    stub = StubCampaign()
    dm = _daemon(tmp_path, node, stub)
    try:
        bf = dm.backfill
        errs0 = counter("serve_backfill_rpc_errors_total")
        assert _wait(lambda: bf.rpc_errors >= 2)
        assert counter("serve_backfill_rpc_errors_total") >= errs0 + 2
        assert 0 < bf._backoff <= bf.max_backoff  # bounded
        assert dm.health()["ok"] is True          # daemon unaffected
        # cursor never moved while the node was down
        assert bf.lo is None or bf.lo == (bf.hi or 0) + 1
        _ChainNode.fail_all = False               # node recovers
        assert _wait(lambda: bf.status()["done"]), bf.status()
        assert bf.ingested == 1
        assert _wait(lambda: dm.store.count() == 1)
    finally:
        dm.scheduler.abort()
        dm.shutdown("test teardown")
