"""Outer plugin discovery (VERDICT r3 missing #10; reference:
``mythril/plugin/discovery.py`` entry-point loading ⚠unv, SURVEY §2 row
"Mythril plugin system (outer)").

Covers both channels: a plugin DIRECTORY of plain .py files (no install
needed) and installed-package entry points (faked via monkeypatched
``importlib.metadata``), plus per-plugin failure isolation.
"""

import textwrap

import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.analysis import ModuleLoader
from mythril_tpu.analysis.module import loader as module_loader
from mythril_tpu.plugin import (LaserPlugin, discover_entrypoints,
                                load_plugin_dir)


@pytest.fixture(autouse=True)
def _isolated_registry():
    """Discovery installs into the process-global detection registry;
    restore it so later tests (exact detector counts, fire_lasers) don't
    see the dummies."""
    saved = list(module_loader._REGISTRY)
    inst = ModuleLoader()
    saved_mods = list(inst._modules)
    yield
    module_loader._REGISTRY[:] = saved
    inst._modules[:] = saved_mods

PLUGIN_SRC = textwrap.dedent("""
    from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
    from mythril_tpu.plugin import LaserPlugin

    class ExternalDetector(DetectionModule):
        name = "ExternalDetector"
        swc_id = "000"
        description = "third-party detection module"

        def _execute(self, ctx):
            return []

    class ExternalHook(LaserPlugin):
        name = "external-hook"

    MYTHRIL_PLUGINS = [ExternalDetector, ExternalHook()]
""")


def test_plugin_dir_registers_modules_and_plugins(tmp_path):
    (tmp_path / "ext.py").write_text(PLUGIN_SRC)
    (tmp_path / "broken.py").write_text("raise RuntimeError('boom')\n")
    disc = load_plugin_dir(str(tmp_path))
    assert "ExternalDetector" in disc.detection_modules
    assert [p.name for p in disc.laser_plugins] == ["external-hook"]
    # a broken file is isolated, not fatal
    assert "broken.py" in disc.errors and "boom" in disc.errors["broken.py"]
    # the detection module is now live in the global registry
    mods = ModuleLoader().get_detection_modules(
        white_list=["ExternalDetector"])
    assert len(mods) == 1 and mods[0].name == "ExternalDetector"


def test_plugin_dir_without_manifest_scans_classes(tmp_path):
    (tmp_path / "bare.py").write_text(textwrap.dedent("""
        from mythril_tpu.plugin import LaserPlugin

        class BarePlugin(LaserPlugin):
            name = "bare"
    """))
    disc = load_plugin_dir(str(tmp_path))
    assert [p.name for p in disc.laser_plugins] == ["bare"]
    assert not disc.errors


def test_entrypoint_discovery(monkeypatch):
    class GoodPlugin(LaserPlugin):
        name = "from-entrypoint"

    class FakeEP:
        def __init__(self, name, obj=None, exc=None):
            self.name, self._obj, self._exc = name, obj, exc

        def load(self):
            if self._exc:
                raise self._exc
            return self._obj

    import importlib.metadata as metadata

    def fake_eps(group=None):
        assert group == "mythril_tpu.plugins"
        return [FakeEP("good", GoodPlugin),
                FakeEP("bad", exc=ImportError("missing dep")),
                FakeEP("junk", obj=42)]

    monkeypatch.setattr(metadata, "entry_points", fake_eps)
    disc = discover_entrypoints()
    assert [p.name for p in disc.laser_plugins] == ["from-entrypoint"]
    assert "bad" in disc.errors and "junk" in disc.errors


def test_cli_list_detectors_with_plugin_dir(tmp_path, capsys):
    from mythril_tpu.interfaces.cli import main

    (tmp_path / "ext2.py").write_text(PLUGIN_SRC.replace(
        "ExternalDetector", "ExternalDetector2"))
    rc = main(["list-detectors", "--plugin-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0 and "ExternalDetector2" in out
