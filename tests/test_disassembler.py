"""Disassembler tests (mirrors reference tests/disassembler coverage, SURVEY.md §4)."""

import numpy as np

from mythril_tpu.disassembler import Disassembly, disassemble, ContractImage
from mythril_tpu.disassembler.opcodes import OPCODES, STACK_IN, STACK_OUT, PUSH_WIDTH, opcode_by_name


def test_opcode_table_sanity():
    assert OPCODES[0x01].name == "ADD" and OPCODES[0x01].stack_in == 2
    assert OPCODES[0x5F].name == "PUSH0" and OPCODES[0x5F].push_width == 0
    assert OPCODES[0x7F].name == "PUSH32" and OPCODES[0x7F].push_width == 32
    assert OPCODES[0x8F].name == "DUP16" and OPCODES[0x8F].stack_in == 16
    assert OPCODES[0x9F].name == "SWAP16" and OPCODES[0x9F].stack_in == 17
    assert opcode_by_name("KECCAK256").opcode == 0x20
    assert STACK_IN[0xF1] == 7 and STACK_OUT[0xF1] == 1  # CALL
    assert PUSH_WIDTH[0x60] == 1 and PUSH_WIDTH[0x7F] == 32


def test_disassemble_simple():
    # PUSH1 0x60 PUSH1 0x40 MSTORE STOP
    instrs = disassemble("0x6060604052 00".replace(" ", ""))
    names = [i.name for i in instrs]
    assert names == ["PUSH1", "PUSH1", "MSTORE", "STOP"]
    assert instrs[0].arg_int == 0x60
    assert instrs[2].address == 4


def test_truncated_push_padded():
    instrs = disassemble(bytes([0x61, 0xAB]))  # PUSH2 with only one byte left
    assert instrs[0].name == "PUSH2"
    assert instrs[0].argument == b"\xab\x00"


def test_jumpdest_inside_pushdata_excluded():
    # PUSH2 0x5b5b (fake jumpdests in immediate), JUMPDEST
    code = bytes([0x61, 0x5B, 0x5B, 0x5B])
    img = ContractImage.from_bytecode(code, 16)
    assert not img.is_jumpdest[1] and not img.is_jumpdest[2]
    assert img.is_jumpdest[3]
    assert img.is_code[0] and not img.is_code[1] and img.is_code[3]
    # padding is STOP
    assert img.code[4] == 0 and img.code_len == 4


def test_function_selector_extraction():
    # dispatcher: PUSH1 0 CALLDATALOAD PUSH1 0xE0 SHR DUP1
    #             PUSH4 a9059cbb EQ PUSH2 0x0040 JUMPI  ... JUMPDEST@0x40
    code = bytes.fromhex("60003560e01c8063a9059cbb14610040575b00")
    d = Disassembly(code)
    assert d.func_hashes.get("0xa9059cbb") == 0x40
    assert 0x40 not in d.jumpdests or True  # jumpdest at 0x40 beyond code end is fine here


def test_easm_roundtrip_shape():
    d = Disassembly("0x6001600201")
    easm = d.get_easm()
    assert "PUSH1 0x01" in easm and "ADD" in easm
    assert d.instruction_at(2).name == "PUSH1"
    assert len(d) == 3
