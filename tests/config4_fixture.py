"""BASELINE config-4 fixture: a 3-contract system at call depth 3.

"Uniswap-V2 core+periphery, inter-contract call depth 3 (multi-tx
symbolic)" — BASELINE.json configs[3]. No solc exists in this image, so
this is the hand-assembled structural equivalent (VERDICT r4 ask #5):

  caller (periphery user entry)
    └─ CALL → router (periphery)
         └─ CALL → vault (core, holds balances + ether)
              └─ CALL → value send (depth 3)

- ``vault``: keccak-mapping balances[caller] (slot-1 keyed), payable
  deposit, guarded withdraw that sends ether back to msg.sender, and a
  BUG: ``sweep()`` sends the whole contract balance to ``tx.origin``
  with no authorization — the classic origin-drain, reachable through
  the full caller→router→vault chain, so only an engine whose frames
  carry calldata/value/returndata across two hops can witness it from
  the caller entry point.
- ``router``: builds sub-call calldata in memory (selector + forwarded
  arg), forwards value on deposit.
- ``caller``: user entry; ``pump()`` deposits via the router,
  ``attack()`` reaches vault.sweep() via the router.
- constructors store the deployer (CALLER) at slot 0 — creation tx +
  message txs is the reference's ``execute_contract_creation`` →
  ``execute_message_call`` sequence (⚠unv, SURVEY §3.2).

Addresses are the corpus defaults (``contract_address(i)``): the trio
must sit at corpus indices (caller=0, router=1, vault=2). The builder
takes a base index so tools/gen_corpus.py can instantiate the shape at
any batch-aligned position.
"""

from mythril_tpu.core.frontier import contract_address
from mythril_tpu.disassembler.asm import (assemble, mapping_key,
                                          selector_prologue)

# selectors (fixed, arbitrary 4-byte ids)
VAULT_DEPOSIT = 0xD0E30DB0    # deposit()
VAULT_WITHDRAW = 0x2E1A7D4D   # withdraw(uint256)
VAULT_SWEEP = 0x6EA056A9      # sweep()  — the unguarded drain
ROUTER_DEPOSIT = 0xB6B55F25
ROUTER_WITHDRAW = 0x38D07436
ROUTER_SWEEP = 0x35FAA416
CALLER_PUMP = 0xD96A094A
CALLER_ATTACK = 0x9E5FAAFC

GAS = ("push3", 200000)


_mapkey = mapping_key  # shared slot convention (disassembler/asm.py)


def _sel_word(selector: int) -> int:
    """selector left-aligned in a 32-byte word (MSTORE at offset 0)."""
    return selector << 224


def vault_runtime() -> bytes:
    return assemble(
        *selector_prologue(),
        "DUP1", VAULT_DEPOSIT, "EQ", ("ref", "deposit"), "JUMPI",
        "DUP1", VAULT_WITHDRAW, "EQ", ("ref", "withdraw"), "JUMPI",
        "DUP1", VAULT_SWEEP, "EQ", ("ref", "sweep"), "JUMPI",
        0, 0, "REVERT",
        # -- deposit(): balances[caller] += callvalue --
        ("label", "deposit"), "POP",
        "CALLVALUE", "CALLER", *_mapkey(1),     # [cv, key]
        "DUP1", "SLOAD",                        # [cv, key, bal]
        "DUP3", "ADD",                          # [cv, key, bal+cv]
        "SWAP1", "SSTORE", "POP", "STOP",
        # -- withdraw(amount): guarded send back to msg.sender --
        ("label", "withdraw"), "POP",
        4, "CALLDATALOAD",                      # [amt]
        "CALLER", *_mapkey(1),                  # [amt, key]
        "DUP1", "SLOAD",                        # [amt, key, bal]
        "DUP3", "DUP2", "LT",                   # bal < amt ?
        ("ref", "insufficient"), "JUMPI",
        "DUP3", "SWAP1", "SUB",                 # [amt, key, bal-amt]
        "SWAP1", "SSTORE",                      # [amt]
        0, 0, 0, 0, "DUP5", "CALLER", GAS, "CALL",
        "POP", "POP", "STOP",
        ("label", "insufficient"), 0, 0, "REVERT",
        # -- sweep(): BUG — whole balance to tx.origin, no auth --
        ("label", "sweep"), "POP",
        0, 0, 0, 0, "SELFBALANCE", "ORIGIN", GAS, "CALL",
        "POP", "STOP",
    )


def router_runtime(base: int = 0) -> bytes:
    vault = contract_address(base + 2)

    def forward(selector: int, args_len: int, value_tokens):
        # calldata in memory: selector word at 0 (+ forwarded arg at 4)
        head = [_sel_word(selector), 0, "MSTORE"]
        if args_len > 4:
            head += [4, "CALLDATALOAD", 4, "MSTORE"]
        return head + [0, 0, args_len, 0, *value_tokens,
                       ("push3", vault), GAS, "CALL", "POP", "STOP"]

    return assemble(
        *selector_prologue(),
        "DUP1", ROUTER_DEPOSIT, "EQ", ("ref", "deposit"), "JUMPI",
        "DUP1", ROUTER_WITHDRAW, "EQ", ("ref", "withdraw"), "JUMPI",
        "DUP1", ROUTER_SWEEP, "EQ", ("ref", "sweep"), "JUMPI",
        0, 0, "REVERT",
        ("label", "deposit"), "POP",
        *forward(VAULT_DEPOSIT, 4, ["CALLVALUE"]),
        ("label", "withdraw"), "POP",
        *forward(VAULT_WITHDRAW, 36, [0]),
        ("label", "sweep"), "POP",
        *forward(VAULT_SWEEP, 4, [0]),
    )


def caller_runtime(base: int = 0) -> bytes:
    router = contract_address(base + 1)

    def forward(selector: int, value_tokens):
        return [_sel_word(selector), 0, "MSTORE",
                0, 0, 4, 0, *value_tokens,
                ("push3", router), GAS, "CALL", "POP", "STOP"]

    return assemble(
        *selector_prologue(),
        "DUP1", CALLER_PUMP, "EQ", ("ref", "pump"), "JUMPI",
        "DUP1", CALLER_ATTACK, "EQ", ("ref", "attack"), "JUMPI",
        0, 0, "REVERT",
        ("label", "pump"), "POP", *forward(ROUTER_DEPOSIT, ["CALLVALUE"]),
        ("label", "attack"), "POP", *forward(ROUTER_SWEEP, [0]),
    )


def constructor() -> bytes:
    """Store the deployer at slot 0, return (runtime supplied by the
    artifact, as solc standard-JSON does — SURVEY §3.1)."""
    return assemble("CALLER", 0, "SSTORE", 0, 0, "RETURN")


def build_system(base: int = 0):
    """[(name, creation, runtime)] for corpus indices base..base+2."""
    return [
        ("Caller", constructor(), caller_runtime(base)),
        ("Router", constructor(), router_runtime(base)),
        ("Vault", constructor(), vault_runtime()),
    ]
