"""Telemetry spine (mythril_tpu/obs, docs/observability.md).

All tests here are engine-free: the tracer/metrics layer is stdlib-only,
and the campaign-side checks use the stub batch runner — the tier-1
budget pays no XLA compile for observability coverage.
"""

import importlib.util
import json
import os
import re
import time

import pytest

from mythril_tpu.obs import metrics as obs_metrics
from mythril_tpu.obs import trace as obs_trace

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Every test starts and ends with no global tracer and a fresh
    metrics registry — telemetry state must never leak between tests."""
    obs_trace.close()
    obs_metrics.REGISTRY.reset()
    yield
    obs_trace.close()
    obs_metrics.REGISTRY.reset()


def read_jsonl(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


# --- tracer -----------------------------------------------------------


def test_span_nesting_and_schema_roundtrip(tmp_path):
    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    with obs_trace.span("outer", bi=3, status="ok"):
        time.sleep(0.01)
        with obs_trace.span("inner", step="halve-lanes"):
            time.sleep(0.002)
    obs_trace.event("degrade", batch=3, step="cpu")
    obs_trace.close()

    events = read_jsonl(str(tmp_path / "t.jsonl"))
    assert len(events) == 3
    # required keys on EVERY event, span or instant
    for e in events:
        assert e["schema"] == obs_trace.SCHEMA
        assert "kind" in e and "t" in e
    # spans close inner-first; attributes round-trip verbatim
    inner, outer, degrade = events
    assert (inner["kind"], inner["name"]) == ("span", "inner")
    assert inner["step"] == "halve-lanes"
    assert (outer["name"], outer["bi"], outer["status"]) == ("outer", 3, "ok")
    assert outer["dur"] >= inner["dur"] > 0
    assert outer["mono"] <= inner["mono"]          # outer started first
    assert degrade["kind"] == "degrade" and degrade["batch"] == 3
    # both clocks on every event
    assert all("mono" in e and "session" in e for e in events)


def test_chrome_trace_json_validity(tmp_path):
    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    with obs_trace.span("batch", bi=0):
        pass
    obs_trace.event("heartbeat", batch=1)
    obs_trace.close()

    doc = json.load(open(t))                       # valid JSON or raises
    evs = doc["traceEvents"]
    assert {e["name"] for e in evs} == {"batch", "heartbeat"}
    for e in evs:
        assert e["ph"] in ("X", "i")
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    x = next(e for e in evs if e["ph"] == "X")
    assert x["dur"] >= 0 and x["args"] == {"bi": 0}


def test_disabled_tracer_is_noop_and_touches_no_files(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    assert obs_trace.get_tracer() is None and not obs_trace.active()
    # zero-allocation: every disabled span is the SAME shared singleton
    s1, s2 = obs_trace.span("a", x=1), obs_trace.span("b")
    assert s1 is s2
    with s1:
        pass
    assert s1.elapsed == 0.0
    assert obs_trace.event("degrade", batch=1) is None
    # timer still measures with tracing off (bench/profilers rely on it)
    with obs_trace.timer("measured") as sp:
        time.sleep(0.005)
    assert sp.elapsed >= 0.004
    assert os.listdir(tmp_path) == []              # no file anywhere


def test_timer_stopwatch_start_stop():
    sw = obs_trace.timer("budget").start()
    time.sleep(0.003)
    live = sw.elapsed
    assert live >= 0.002
    dur = sw.stop()
    assert dur >= live and sw.elapsed == dur       # frozen after stop


def test_jsonl_path_derivation():
    assert obs_trace.jsonl_path_for("t.json") == "t.jsonl"
    assert obs_trace.jsonl_path_for("out/trace") == "out/trace.jsonl"


# --- distributed tracing ----------------------------------------------


def test_trace_context_stamps_and_indexes(tmp_path):
    """Inside a trace_context scope every span/event is stamped with
    trace_id + span/parent linkage, lands in the bounded trace index
    under EVERY linked id, and reads back in monotonic order
    (docs/observability.md "Distributed tracing")."""
    obs_trace.configure(str(tmp_path / "t.json"))
    tid, other = "a" * 16, "b" * 16
    assert obs_trace.trace_records(tid) is None
    with obs_trace.trace_context(tid, link_ids=[other]):
        assert obs_trace.current_trace_id() == tid
        with obs_trace.span("schedule", bi=0):
            obs_trace.event("verdict_commit", eid="e0")
    assert obs_trace.current_trace_id() is None    # scope exited
    recs = obs_trace.trace_records(tid)
    assert recs is not None
    sp = next(r for r in recs if r["kind"] == "span")
    ev = next(r for r in recs if r["kind"] == "verdict_commit")
    assert sp["trace_id"] == tid and ev["trace_id"] == tid
    # the event nested under the span links to it as parent
    assert ev["parent"] == sp["span"]
    # the linked (batched-together) request indexes the same records
    assert obs_trace.trace_records(other)
    monos = [r["mono"] for r in recs]
    assert monos == sorted(monos)


def test_context_snapshot_roundtrip(tmp_path):
    """The snapshot/apply pair that crosses thread and IPC boundaries
    reproduces the scope verbatim; apply(None) is a no-op guard."""
    with obs_trace.trace_context("c" * 16, link_ids=["d" * 16]):
        snap = obs_trace.context_snapshot()
    assert snap["ids"] == ["c" * 16, "d" * 16]
    assert obs_trace.context_snapshot() is None
    with obs_trace.apply_context(snap):
        assert obs_trace.current_trace_id() == "c" * 16
    with obs_trace.apply_context(None):
        assert obs_trace.current_trace_id() is None


def test_worker_clock_stitch_monotone(tmp_path):
    """Backhauled worker records carry the CHILD's monotonic clock;
    re-emission with the spawn-handshake offset must land them on the
    parent timeline — after the parent span that contains them, in
    child order — even under an arbitrarily skewed child clock."""
    obs_trace.configure(str(tmp_path / "t.json"))
    tid = "e" * 16
    with obs_trace.trace_context(tid):
        with obs_trace.span("schedule", bi=0):
            # fake child: its monotonic clock reads ~5.0 while the
            # parent's reads time.monotonic() — wildly skewed
            child = [
                {"schema": 1, "kind": "span", "name": "device_phase",
                 "t": 123.0, "mono": 5.0, "dur": 0.25, "tid": 1,
                 "session": "fakewkr", "bi": 0, "trace_id": tid},
                {"schema": 1, "kind": "solver_stage", "t": 123.3,
                 "mono": 5.3, "session": "fakewkr", "stage": "lru",
                 "verdict": "unsat", "trace_id": tid},
            ]
            offset = time.monotonic() - 5.0   # the supervisor handshake
            n = obs_trace.reemit_records(child, mono_offset=offset,
                                         proc="worker", wpid=1234)
    obs_trace.close()
    assert n == 2
    recs = obs_trace.trace_records(tid)
    worker = [r for r in recs if r.get("proc") == "worker"]
    assert len(worker) == 2
    # transport meta was re-stamped; the child session survives as
    # provenance, not as the ordering key
    assert all(r["src_session"] == "fakewkr" for r in worker)
    assert all(r["session"] != "fakewkr" for r in worker)
    # ONE monotone timeline on the parent clock: the worker device
    # span starts after the parent schedule span that dispatched it
    monos = [r["mono"] for r in recs]
    assert monos == sorted(monos)
    sched = next(r for r in recs if r.get("name") == "schedule")
    dev = next(r for r in recs if r.get("name") == "device_phase")
    stage = next(r for r in recs if r["kind"] == "solver_stage")
    assert sched["mono"] <= dev["mono"] <= stage["mono"]


def test_jsonl_rotation_set_aside_and_byte_gauge(tmp_path):
    """Crossing the size cap rotates the live log to ``.1`` (one
    set-aside generation), opens the fresh log with a
    ``trace_log_rotated`` seam record, ticks the rotation counter and
    keeps the obs_event_log_bytes gauge on the live file."""
    jl = str(tmp_path / "t.jsonl")
    obs_trace.configure(str(tmp_path / "t.json"), max_jsonl_bytes=600)
    for i in range(30):
        obs_trace.event("heartbeat", batch=i, pad="x" * 40)
    snap = obs_metrics.REGISTRY.snapshot()
    assert snap["counters"]["obs_event_log_rotations_total"] >= 1
    assert os.path.exists(jl + ".1")
    assert read_jsonl(jl + ".1")                   # parseable prefix
    live = read_jsonl(jl)
    assert live[0]["kind"] == "trace_log_rotated"
    assert live[0]["rotated_bytes"] >= 600
    assert live[0]["set_aside"] == jl + ".1"
    assert snap["gauges"]["obs_event_log_bytes"] == os.path.getsize(jl)
    obs_trace.close()


def test_worker_buffer_drain_and_drop_counter():
    """Buffer-mode (engine-worker) tracer: records accumulate for the
    batch-reply drain and touch no files; a record arriving after
    close is DECLARED via obs_events_dropped_total, never silent."""
    tr = obs_trace.configure(buffer=True)
    with obs_trace.trace_context("f" * 16):
        obs_trace.event("solver_stage", stage="lru", verdict="unsat")
    recs = tr.drain_buffer()
    assert len(recs) == 1 and recs[0]["trace_id"] == "f" * 16
    assert tr.drain_buffer() == []                 # drained
    tr.close()
    obs_trace.event("heartbeat", batch=1)
    snap = obs_metrics.REGISTRY.snapshot()
    assert snap["counters"]["obs_events_dropped_total"] == 1.0


# --- schema lint: source scan vs naming rules and the docs table ------

_METRIC_CALL = re.compile(
    r'(?:counter|gauge|histogram)\(\s*[\'"]([A-Za-z0-9_]+)[\'"]')
_EVENT_CALL = re.compile(r'\b_?event\(\s*[\'"]([A-Za-z0-9_]+)[\'"]')
_PROM_NAME = re.compile(r"^[a-z][a-z0-9_]*$")


def _scan_sources():
    """Every metric-name and event-kind literal in the package (the
    regexes span the multi-line call style used everywhere)."""
    metrics, events = set(), set()
    for dirpath, _dirs, files in os.walk(os.path.join(ROOT,
                                                      "mythril_tpu")):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn), encoding="utf-8") as fh:
                src = fh.read()
            metrics.update(_METRIC_CALL.findall(src))
            events.update(_EVENT_CALL.findall(src))
    return metrics, events


def test_metric_names_follow_prometheus_conventions():
    metrics, _ = _scan_sources()
    assert len(metrics) > 40                       # the scan works
    bad = sorted(m for m in metrics
                 if not _PROM_NAME.match(m) or "__" in m
                 or m.endswith("_"))
    assert not bad, f"metric names violating prometheus naming: {bad}"


def test_every_event_kind_is_documented():
    """Every emitted event ``kind`` must appear (backticked) in
    docs/observability.md's schema table — adding an event without
    documenting it fails here."""
    _, events = _scan_sources()
    # dynamic prefix concatenations (event("tier_" + kind)) scan as
    # the prefix; their concrete kinds also appear as literals
    events = {e for e in events if not e.endswith("_")}
    events.add("trace_log_rotated")    # written inline at the seam
    with open(os.path.join(ROOT, "docs", "observability.md"),
              encoding="utf-8") as fh:
        doc = fh.read()
    missing = sorted(k for k in events if f"`{k}`" not in doc)
    assert not missing, ("event kinds missing from "
                         f"docs/observability.md: {missing}")


# --- metrics ----------------------------------------------------------


def test_metrics_snapshot_shape_and_prometheus():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("batches_total").inc()
    reg.counter("batches_total").inc(2)
    reg.gauge("frontier_occupancy").set(0.75)
    h = reg.histogram("batch_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(30.0)

    snap = reg.snapshot()
    assert snap["schema"] == obs_metrics.SCHEMA and "t" in snap
    assert snap["counters"]["batches_total"] == 3.0
    assert snap["gauges"]["frontier_occupancy"] == 0.75
    hs = snap["histograms"]["batch_seconds"]
    assert (hs["count"], hs["min"], hs["max"]) == (3, 0.05, 30.0)
    assert hs["sum"] == pytest.approx(30.55)
    # cumulative le semantics, +Inf covers everything
    assert hs["buckets"] == {"0.1": 1, "1.0": 2, "+Inf": 3}

    prom = reg.to_prometheus()
    assert "# TYPE mythril_batches_total counter" in prom
    assert "mythril_batches_total 3" in prom
    assert "# TYPE mythril_batch_seconds histogram" in prom
    assert 'mythril_batch_seconds_bucket{le="+Inf"} 3' in prom
    assert "mythril_batch_seconds_count 3" in prom
    # same-name re-registration under a different type is a bug
    with pytest.raises(TypeError):
        reg.gauge("batches_total")


def test_metrics_labeled_series_share_one_family():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("shed_total", help="sheds",
                labels={"reason": "depth"}).inc()
    reg.counter("shed_total", labels={"reason": "age"}).inc(2)
    reg.gauge("inflight", labels={"tenant": "a"}).set(3)
    # label order is canonicalized: same labels -> same series
    assert (obs_metrics.label_key("x", {"b": 1, "a": 2})
            == obs_metrics.label_key("x", {"a": 2, "b": 1}))
    prom = reg.to_prometheus()
    # ONE header block for the family, one sample line per series
    assert prom.count("# TYPE mythril_shed_total counter") == 1
    assert 'mythril_shed_total{reason="depth"} 1' in prom
    assert 'mythril_shed_total{reason="age"} 2' in prom
    assert 'mythril_inflight{tenant="a"} 3' in prom
    # snapshot keys carry the label block (JSON-side disambiguation)
    snap = reg.snapshot()
    assert snap["counters"]['shed_total{reason="age"}'] == 2.0
    # label values are escaped, never able to break the line format
    reg.counter("esc_total", labels={"v": 'a"b\nc'}).inc()
    assert 'mythril_esc_total{v="a\\"b c"} 1' in reg.to_prometheus()


def test_metrics_write_json_and_prom(tmp_path):
    reg = obs_metrics.MetricsRegistry()
    reg.counter("c").inc()
    j = str(tmp_path / "m.json")
    p = str(tmp_path / "m.prom")
    reg.write(j)
    reg.write(p)
    assert json.load(open(j))["counters"]["c"] == 1.0
    assert "mythril_c 1" in open(p).read()


def test_histogram_quantile():
    reg = obs_metrics.MetricsRegistry()
    h = reg.histogram("lat_seconds", buckets=(0.1, 0.5, 1.0))
    assert h.quantile(0.5) is None                 # empty
    for v in (0.05, 0.2, 0.3, 0.8):
        h.observe(v)
    # bucket-walk estimate, clamped to the observed max
    assert h.quantile(0.5) == 0.5
    assert h.quantile(0.95) == 0.8


def test_metrics_delta_roundtrip():
    """snapshot_delta/apply_delta — the worker-telemetry metrics
    backhaul: only what changed crosses the IPC boundary, and folding
    it into the parent registry reproduces the increments."""
    reg = obs_metrics.MetricsRegistry()
    reg.counter("c_total").inc(2)
    h = reg.histogram("h_seconds", buckets=(1.0,))
    h.observe(0.5)
    before = reg.snapshot()
    reg.counter("c_total").inc(3)
    h.observe(2.0)
    reg.gauge("g").set(7)
    delta = obs_metrics.snapshot_delta(reg.snapshot(), before)
    assert delta["counters"] == {"c_total": 3.0}
    dst = obs_metrics.MetricsRegistry()
    dst.histogram("h_seconds", buckets=(1.0,))     # same shape
    obs_metrics.apply_delta(delta, dst)
    snap = dst.snapshot()
    assert snap["counters"]["c_total"] == 3.0
    assert snap["gauges"]["g"] == 7.0
    hs = snap["histograms"]["h_seconds"]
    assert hs["count"] == 1 and hs["sum"] == 2.0
    assert hs["buckets"] == {"1.0": 0, "+Inf": 1}
    # an unchanged registry produces an EMPTY delta
    again = reg.snapshot()
    d2 = obs_metrics.snapshot_delta(again, again)
    assert not d2["counters"] and not d2["histograms"]


# --- campaign integration (stub runner — no engine) -------------------

N = 6
STUB_CONTRACTS = [(f"c{i:03d}", b"\x00") for i in range(N)]


def _stub_runner(bi, names, codes, lanes=None, width=None):
    return {"issues": [], "paths": len(names), "dropped": 0, "iprof": {}}


def _campaign(ckpt, fault=None, **kw):
    from mythril_tpu.mythril.campaign import CorpusCampaign
    from mythril_tpu.resilience import FaultInjector

    return CorpusCampaign(
        STUB_CONTRACTS, batch_size=2, checkpoint_dir=ckpt, spec=object(),
        batch_timeout=5.0, batch_runner=_stub_runner,
        fault_injector=FaultInjector.from_string(fault), **kw)


def test_campaign_events_carry_wall_mono_and_session(tmp_path):
    res = _campaign(str(tmp_path / "ck"), "oom:batch=1:times=1").run()
    degr = [e for e in res.backend_events if e["kind"] == "degrade"]
    assert degr
    for e in degr:
        assert e["t"] > 1e9                        # wall clock (epoch)
        assert isinstance(e["mono"], float)        # monotonic clock
        assert isinstance(e["session"], str) and e["session"]
    # one campaign instance = one session token on all its events
    assert len({e["session"] for e in degr}) == 1


def test_campaign_trace_bus_and_heartbeat_cadence(tmp_path, capsys):
    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    # heartbeat_every=0: a beat after EVERY batch
    res = _campaign(str(tmp_path / "ck"), heartbeat_every=0.0).run()
    obs_trace.close()
    assert res.batches == 3
    beats = [line for line in capsys.readouterr().err.splitlines()
             if line.startswith("heartbeat: ")]
    assert len(beats) == 3
    # the pulse carries the promised fields
    assert "contracts 6/6" in beats[-1]
    assert "paths/s" in beats[-1] and "ckpt-age" in beats[-1]
    events = read_jsonl(str(tmp_path / "t.jsonl"))
    kinds = [e["kind"] for e in events]
    assert kinds.count("heartbeat") == 3
    assert kinds.count("batch_status") == 3
    assert sum(1 for e in events
               if e["kind"] == "span" and e["name"] == "batch") == 3
    # every bus event satisfies the soak's schema contract
    assert all("kind" in e and "t" in e and "schema" in e for e in events)


def test_campaign_heartbeat_rate_limited(tmp_path, capsys):
    # a huge interval -> exactly one beat (the immediate first one)
    _campaign(str(tmp_path / "ck"), heartbeat_every=3600.0).run()
    beats = [line for line in capsys.readouterr().err.splitlines()
             if line.startswith("heartbeat: ")]
    assert len(beats) == 1


def test_campaign_batch_metrics(tmp_path):
    _campaign(str(tmp_path / "ck"), "raise:contract=c002").run()
    snap = obs_metrics.REGISTRY.snapshot()
    assert snap["counters"]["batches_total"] == 3.0
    assert snap["counters"]["contracts_quarantined_total"] == 1.0
    assert snap["counters"]["batch_retries_total"] == 1.0
    assert snap["histograms"]["batch_seconds"]["count"] == 3
    assert snap["histograms"]["checkpoint_write_seconds"]["count"] >= 3


def test_merge_campaigns_orders_events_by_session_then_time():
    from mythril_tpu.mythril.campaign import merge_campaigns

    # host A resumed once: session a1 (t 10..11) then a2 (t 20..21);
    # host B's single session overlaps both in wall time. Concatenation
    # order deliberately interleaves; the merge must group per session
    # and order within each by timestamp, stably.
    ra = {"backend_events": [
        {"kind": "x1", "t": 20.0, "session": "a2"},
        {"kind": "x2", "t": 21.0, "session": "a2"},
        {"kind": "x3", "t": 10.0, "session": "a1"},
        {"kind": "tie1", "t": 11.0, "session": "a1"},
        {"kind": "tie2", "t": 11.0, "session": "a1"},
    ]}
    rb = {"backend_events": [{"kind": "y1", "t": 15.0, "session": "b1"}]}
    got = merge_campaigns([ra, rb])["backend_events"]
    assert [e["kind"] for e in got] == ["x3", "tie1", "tie2", "x1", "x2",
                                       "y1"]
    # legacy events without session/t keep their relative order, first
    legacy = {"backend_events": [{"kind": "old1"}, {"kind": "old2"}]}
    got = merge_campaigns([legacy, rb])["backend_events"]
    assert [e["kind"] for e in got] == ["old1", "old2", "y1"]


def test_checkpoint_save_emits_span_and_latency(tmp_path):
    from mythril_tpu.utils.checkpoint import (load_json_checkpoint,
                                              save_json_checkpoint)

    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    p = str(tmp_path / "state.json")
    save_json_checkpoint(p, {"next_batch": 2})
    assert load_json_checkpoint(p)["next_batch"] == 2
    obs_trace.close()
    names = [e.get("name") for e in read_jsonl(str(tmp_path / "t.jsonl"))]
    assert "checkpoint_save" in names and "checkpoint_load" in names
    h = obs_metrics.REGISTRY.snapshot()["histograms"]
    assert h["checkpoint_write_seconds"]["count"] == 1


# --- trace_report tool ------------------------------------------------


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(ROOT, "tools", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_report_summarizes_both_formats(tmp_path, capsys):
    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    _campaign(str(tmp_path / "ck"), "oom:batch=1:times=1").run()
    obs_trace.close()

    tr = _load_trace_report()
    for path in (t, str(tmp_path / "t.jsonl")):
        assert tr.main([path]) == 0
        out = capsys.readouterr().out
        assert "top spans by total wall time" in out
        assert "batch stall table" in out
        assert "halve-lanes" in out                # degrade timeline row
        assert "checkpoint_save" in out or "saves:" in out
    assert tr.main([str(tmp_path / "nope.json")]) == 2


def test_trace_report_cross_process_timeline(tmp_path, capsys):
    """Section 10 regroups trace_id-stamped records per request and
    renders worker-side records (backhauled spans) as [worker] rows in
    one monotone timeline."""
    obs_trace.configure(str(tmp_path / "t.json"))
    tid = "9" * 16
    with obs_trace.trace_context(tid):
        with obs_trace.span("schedule", bi=0):
            obs_trace.reemit_records(
                [{"schema": 1, "kind": "span", "name": "device_phase",
                  "t": 1.0, "mono": 0.5, "dur": 0.2,
                  "session": "fakewkr", "trace_id": tid}],
                mono_offset=time.monotonic() - 0.5, proc="worker")
        obs_trace.event("verdict_commit", eid="e0")
    obs_trace.close()
    tr = _load_trace_report()
    assert tr.main([str(tmp_path / "t.jsonl")]) == 0
    out = capsys.readouterr().out
    assert "cross-process timeline" in out
    assert f"trace {tid}" in out
    assert "[worker]" in out and "device_phase" in out
    assert "verdict_commit" in out
    # the per-stage totals table names the parent-side span too
    assert "schedule" in out
