"""Telemetry spine (mythril_tpu/obs, docs/observability.md).

All tests here are engine-free: the tracer/metrics layer is stdlib-only,
and the campaign-side checks use the stub batch runner — the tier-1
budget pays no XLA compile for observability coverage.
"""

import importlib.util
import json
import os
import time

import pytest

from mythril_tpu.obs import metrics as obs_metrics
from mythril_tpu.obs import trace as obs_trace

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Every test starts and ends with no global tracer and a fresh
    metrics registry — telemetry state must never leak between tests."""
    obs_trace.close()
    obs_metrics.REGISTRY.reset()
    yield
    obs_trace.close()
    obs_metrics.REGISTRY.reset()


def read_jsonl(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


# --- tracer -----------------------------------------------------------


def test_span_nesting_and_schema_roundtrip(tmp_path):
    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    with obs_trace.span("outer", bi=3, status="ok"):
        time.sleep(0.01)
        with obs_trace.span("inner", step="halve-lanes"):
            time.sleep(0.002)
    obs_trace.event("degrade", batch=3, step="cpu")
    obs_trace.close()

    events = read_jsonl(str(tmp_path / "t.jsonl"))
    assert len(events) == 3
    # required keys on EVERY event, span or instant
    for e in events:
        assert e["schema"] == obs_trace.SCHEMA
        assert "kind" in e and "t" in e
    # spans close inner-first; attributes round-trip verbatim
    inner, outer, degrade = events
    assert (inner["kind"], inner["name"]) == ("span", "inner")
    assert inner["step"] == "halve-lanes"
    assert (outer["name"], outer["bi"], outer["status"]) == ("outer", 3, "ok")
    assert outer["dur"] >= inner["dur"] > 0
    assert outer["mono"] <= inner["mono"]          # outer started first
    assert degrade["kind"] == "degrade" and degrade["batch"] == 3
    # both clocks on every event
    assert all("mono" in e and "session" in e for e in events)


def test_chrome_trace_json_validity(tmp_path):
    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    with obs_trace.span("batch", bi=0):
        pass
    obs_trace.event("heartbeat", batch=1)
    obs_trace.close()

    doc = json.load(open(t))                       # valid JSON or raises
    evs = doc["traceEvents"]
    assert {e["name"] for e in evs} == {"batch", "heartbeat"}
    for e in evs:
        assert e["ph"] in ("X", "i")
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    x = next(e for e in evs if e["ph"] == "X")
    assert x["dur"] >= 0 and x["args"] == {"bi": 0}


def test_disabled_tracer_is_noop_and_touches_no_files(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    assert obs_trace.get_tracer() is None and not obs_trace.active()
    # zero-allocation: every disabled span is the SAME shared singleton
    s1, s2 = obs_trace.span("a", x=1), obs_trace.span("b")
    assert s1 is s2
    with s1:
        pass
    assert s1.elapsed == 0.0
    assert obs_trace.event("degrade", batch=1) is None
    # timer still measures with tracing off (bench/profilers rely on it)
    with obs_trace.timer("measured") as sp:
        time.sleep(0.005)
    assert sp.elapsed >= 0.004
    assert os.listdir(tmp_path) == []              # no file anywhere


def test_timer_stopwatch_start_stop():
    sw = obs_trace.timer("budget").start()
    time.sleep(0.003)
    live = sw.elapsed
    assert live >= 0.002
    dur = sw.stop()
    assert dur >= live and sw.elapsed == dur       # frozen after stop


def test_jsonl_path_derivation():
    assert obs_trace.jsonl_path_for("t.json") == "t.jsonl"
    assert obs_trace.jsonl_path_for("out/trace") == "out/trace.jsonl"


# --- metrics ----------------------------------------------------------


def test_metrics_snapshot_shape_and_prometheus():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("batches_total").inc()
    reg.counter("batches_total").inc(2)
    reg.gauge("frontier_occupancy").set(0.75)
    h = reg.histogram("batch_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(30.0)

    snap = reg.snapshot()
    assert snap["schema"] == obs_metrics.SCHEMA and "t" in snap
    assert snap["counters"]["batches_total"] == 3.0
    assert snap["gauges"]["frontier_occupancy"] == 0.75
    hs = snap["histograms"]["batch_seconds"]
    assert (hs["count"], hs["min"], hs["max"]) == (3, 0.05, 30.0)
    assert hs["sum"] == pytest.approx(30.55)
    # cumulative le semantics, +Inf covers everything
    assert hs["buckets"] == {"0.1": 1, "1.0": 2, "+Inf": 3}

    prom = reg.to_prometheus()
    assert "# TYPE mythril_batches_total counter" in prom
    assert "mythril_batches_total 3" in prom
    assert "# TYPE mythril_batch_seconds histogram" in prom
    assert 'mythril_batch_seconds_bucket{le="+Inf"} 3' in prom
    assert "mythril_batch_seconds_count 3" in prom
    # same-name re-registration under a different type is a bug
    with pytest.raises(TypeError):
        reg.gauge("batches_total")


def test_metrics_labeled_series_share_one_family():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("shed_total", help="sheds",
                labels={"reason": "depth"}).inc()
    reg.counter("shed_total", labels={"reason": "age"}).inc(2)
    reg.gauge("inflight", labels={"tenant": "a"}).set(3)
    # label order is canonicalized: same labels -> same series
    assert (obs_metrics.label_key("x", {"b": 1, "a": 2})
            == obs_metrics.label_key("x", {"a": 2, "b": 1}))
    prom = reg.to_prometheus()
    # ONE header block for the family, one sample line per series
    assert prom.count("# TYPE mythril_shed_total counter") == 1
    assert 'mythril_shed_total{reason="depth"} 1' in prom
    assert 'mythril_shed_total{reason="age"} 2' in prom
    assert 'mythril_inflight{tenant="a"} 3' in prom
    # snapshot keys carry the label block (JSON-side disambiguation)
    snap = reg.snapshot()
    assert snap["counters"]['shed_total{reason="age"}'] == 2.0
    # label values are escaped, never able to break the line format
    reg.counter("esc_total", labels={"v": 'a"b\nc'}).inc()
    assert 'mythril_esc_total{v="a\\"b c"} 1' in reg.to_prometheus()


def test_metrics_write_json_and_prom(tmp_path):
    reg = obs_metrics.MetricsRegistry()
    reg.counter("c").inc()
    j = str(tmp_path / "m.json")
    p = str(tmp_path / "m.prom")
    reg.write(j)
    reg.write(p)
    assert json.load(open(j))["counters"]["c"] == 1.0
    assert "mythril_c 1" in open(p).read()


# --- campaign integration (stub runner — no engine) -------------------

N = 6
STUB_CONTRACTS = [(f"c{i:03d}", b"\x00") for i in range(N)]


def _stub_runner(bi, names, codes, lanes=None, width=None):
    return {"issues": [], "paths": len(names), "dropped": 0, "iprof": {}}


def _campaign(ckpt, fault=None, **kw):
    from mythril_tpu.mythril.campaign import CorpusCampaign
    from mythril_tpu.resilience import FaultInjector

    return CorpusCampaign(
        STUB_CONTRACTS, batch_size=2, checkpoint_dir=ckpt, spec=object(),
        batch_timeout=5.0, batch_runner=_stub_runner,
        fault_injector=FaultInjector.from_string(fault), **kw)


def test_campaign_events_carry_wall_mono_and_session(tmp_path):
    res = _campaign(str(tmp_path / "ck"), "oom:batch=1:times=1").run()
    degr = [e for e in res.backend_events if e["kind"] == "degrade"]
    assert degr
    for e in degr:
        assert e["t"] > 1e9                        # wall clock (epoch)
        assert isinstance(e["mono"], float)        # monotonic clock
        assert isinstance(e["session"], str) and e["session"]
    # one campaign instance = one session token on all its events
    assert len({e["session"] for e in degr}) == 1


def test_campaign_trace_bus_and_heartbeat_cadence(tmp_path, capsys):
    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    # heartbeat_every=0: a beat after EVERY batch
    res = _campaign(str(tmp_path / "ck"), heartbeat_every=0.0).run()
    obs_trace.close()
    assert res.batches == 3
    beats = [line for line in capsys.readouterr().err.splitlines()
             if line.startswith("heartbeat: ")]
    assert len(beats) == 3
    # the pulse carries the promised fields
    assert "contracts 6/6" in beats[-1]
    assert "paths/s" in beats[-1] and "ckpt-age" in beats[-1]
    events = read_jsonl(str(tmp_path / "t.jsonl"))
    kinds = [e["kind"] for e in events]
    assert kinds.count("heartbeat") == 3
    assert kinds.count("batch_status") == 3
    assert sum(1 for e in events
               if e["kind"] == "span" and e["name"] == "batch") == 3
    # every bus event satisfies the soak's schema contract
    assert all("kind" in e and "t" in e and "schema" in e for e in events)


def test_campaign_heartbeat_rate_limited(tmp_path, capsys):
    # a huge interval -> exactly one beat (the immediate first one)
    _campaign(str(tmp_path / "ck"), heartbeat_every=3600.0).run()
    beats = [line for line in capsys.readouterr().err.splitlines()
             if line.startswith("heartbeat: ")]
    assert len(beats) == 1


def test_campaign_batch_metrics(tmp_path):
    _campaign(str(tmp_path / "ck"), "raise:contract=c002").run()
    snap = obs_metrics.REGISTRY.snapshot()
    assert snap["counters"]["batches_total"] == 3.0
    assert snap["counters"]["contracts_quarantined_total"] == 1.0
    assert snap["counters"]["batch_retries_total"] == 1.0
    assert snap["histograms"]["batch_seconds"]["count"] == 3
    assert snap["histograms"]["checkpoint_write_seconds"]["count"] >= 3


def test_merge_campaigns_orders_events_by_session_then_time():
    from mythril_tpu.mythril.campaign import merge_campaigns

    # host A resumed once: session a1 (t 10..11) then a2 (t 20..21);
    # host B's single session overlaps both in wall time. Concatenation
    # order deliberately interleaves; the merge must group per session
    # and order within each by timestamp, stably.
    ra = {"backend_events": [
        {"kind": "x1", "t": 20.0, "session": "a2"},
        {"kind": "x2", "t": 21.0, "session": "a2"},
        {"kind": "x3", "t": 10.0, "session": "a1"},
        {"kind": "tie1", "t": 11.0, "session": "a1"},
        {"kind": "tie2", "t": 11.0, "session": "a1"},
    ]}
    rb = {"backend_events": [{"kind": "y1", "t": 15.0, "session": "b1"}]}
    got = merge_campaigns([ra, rb])["backend_events"]
    assert [e["kind"] for e in got] == ["x3", "tie1", "tie2", "x1", "x2",
                                       "y1"]
    # legacy events without session/t keep their relative order, first
    legacy = {"backend_events": [{"kind": "old1"}, {"kind": "old2"}]}
    got = merge_campaigns([legacy, rb])["backend_events"]
    assert [e["kind"] for e in got] == ["old1", "old2", "y1"]


def test_checkpoint_save_emits_span_and_latency(tmp_path):
    from mythril_tpu.utils.checkpoint import (load_json_checkpoint,
                                              save_json_checkpoint)

    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    p = str(tmp_path / "state.json")
    save_json_checkpoint(p, {"next_batch": 2})
    assert load_json_checkpoint(p)["next_batch"] == 2
    obs_trace.close()
    names = [e.get("name") for e in read_jsonl(str(tmp_path / "t.jsonl"))]
    assert "checkpoint_save" in names and "checkpoint_load" in names
    h = obs_metrics.REGISTRY.snapshot()["histograms"]
    assert h["checkpoint_write_seconds"]["count"] == 1


# --- trace_report tool ------------------------------------------------


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(ROOT, "tools", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_report_summarizes_both_formats(tmp_path, capsys):
    t = str(tmp_path / "t.json")
    obs_trace.configure(t)
    _campaign(str(tmp_path / "ck"), "oom:batch=1:times=1").run()
    obs_trace.close()

    tr = _load_trace_report()
    for path in (t, str(tmp_path / "t.jsonl")):
        assert tr.main([path]) == 0
        out = capsys.readouterr().out
        assert "top spans by total wall time" in out
        assert "batch stall table" in out
        assert "halve-lanes" in out                # degrade timeline row
        assert "checkpoint_save" in out or "saves:" in out
    assert tr.main([str(tmp_path / "nope.json")]) == 2
