"""Test harness: force JAX onto CPU with 8 virtual devices.

Mirrors the reference's "no chain needed" test philosophy (SURVEY.md §4):
the reference tests LASER with hand-built fixtures and mocked RPC; we test
the TPU framework on a virtual 8-device CPU mesh so CI needs no TPU.
``tests/test_sharding.py`` shards the symbolic engine's lane axis over
this mesh and asserts bit-equivalence with the unsharded run; the other
suites run single-device.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mythril_tpu  # noqa: E402,F401  (enables x64)

import jax  # noqa: E402

# The axon sitecustomize force-sets jax_platforms="axon,cpu", which overrides
# the JAX_PLATFORMS env var above — pin the CPU backend programmatically so
# the 8 virtual host devices actually materialize.
try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # backend already initialized by an earlier plugin import

# Persistent compilation cache: the superstep graph is large and this box has
# one core — cache compiled executables across test runs. A crashed writer
# can leave a corrupt entry that segfaults later readers; wipe .jax_cache
# or set MYTHRIL_NO_JAX_CACHE=1 if the suite dies inside jax compile/cache
# frames.
if os.environ.get("MYTHRIL_NO_JAX_CACHE") != "1":
    # per-xdist-worker cache dir: concurrent workers must not race writes
    # into one cache (worker ids are stable, so reuse across runs holds)
    _worker = os.environ.get("PYTEST_XDIST_WORKER", "gw0")
    _CACHE_DIR = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        f".jax_cache_{_worker}")
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # engine-worker SUBPROCESSES (mythril_tpu/engine_worker.py) share
    # the same persistent cache via this env var — jax.config updates
    # don't cross the spawn, and a cold worker would otherwise pay the
    # full superstep compile on this one-core box
    os.environ.setdefault("MYTHRIL_WORKER_JAX_CACHE", _CACHE_DIR)
