"""CLI + orchestration layer (VERDICT r2 ask #6).

Reference: ``tests/cmd_line_test.py`` / ``tests/test_cli_opts.py`` (⚠unv,
SURVEY.md §4 "CLI tests") — arg parsing, output formats, command flow.
Runs in-process via ``cli.main`` (a subprocess would re-pay jax startup).
"""

import json

import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.interfaces.cli import create_parser, main
from mythril_tpu.mythril import (MythrilAnalyzer, MythrilConfig,
                                 MythrilDisassembler)
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.symbolic import SymSpec

# unprotected SELFDESTRUCT — one-instruction finding, fast to analyze
KILLABLE = assemble(0, "SELFDESTRUCT").hex()


def run_cli(capsys, *argv):
    rc = main(list(argv))
    out = capsys.readouterr().out
    return rc, out


def test_version(capsys):
    rc, out = run_cli(capsys, "version")
    assert rc == 0 and out.startswith("mythril_tpu ")


def test_list_detectors(capsys):
    rc, out = run_cli(capsys, "list-detectors")
    assert rc == 0
    assert "AccidentallyKillable" in out and "SWC-106" in out
    assert len(out.strip().splitlines()) >= 15


def test_disassemble(capsys):
    rc, out = run_cli(capsys, "d", "-c", "600160020100")
    assert rc == 0
    assert "PUSH1 0x01" in out and "ADD" in out


def test_analyze_json(capsys):
    rc, out = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-steps", "32", "--lanes-per-contract", "4",
        "--limits-profile", "test",
        "-m", "AccidentallyKillable", "-o", "json",
    )
    assert rc == 0
    payload = json.loads(out)
    assert payload["success"] is True
    swcs = {i["swc-id"] for i in payload["issues"]}
    assert "106" in swcs


def test_analyze_text_from_file(tmp_path, capsys):
    f = tmp_path / "code.hex"
    f.write_text("0x" + KILLABLE)
    rc, out = run_cli(
        capsys, "a", "-f", str(f), "-t", "1", "--max-steps", "32",
        "--lanes-per-contract", "4", "--limits-profile", "test",
        "-m", "AccidentallyKillable",
    )
    assert rc == 0
    assert "Unprotected SELFDESTRUCT" in out


def test_missing_input_errors():
    with pytest.raises(SystemExit):
        main(["analyze"])


def test_parser_reference_flags():
    p = create_parser()
    args = p.parse_args([
        "analyze", "-c", "00", "-t", "3", "-m", "EtherThief,TxOrigin",
        "-o", "markdown", "--loop-bound", "2", "--execution-timeout", "10",
    ])
    assert args.transaction_count == 3
    assert args.loop_bound == 2
    assert args.execution_timeout == 10.0


def test_orchestration_creation_path():
    # MythrilAnalyzer threads creation bytecode into the creation tx
    ctor = assemble("CALLER", 0, "SSTORE", 0, 0, "RETURN")
    runtime = assemble(0, "SLOAD", 1, "SSTORE", "STOP")
    contract = MythrilDisassembler.load_from_bytecode(
        runtime.hex(), creation_code=ctor.hex(), name="Owned")
    cfg = MythrilConfig(limits=TEST_LIMITS, spec=SymSpec(storage=False),
                        transaction_count=1, max_steps=128,
                        lanes_per_contract=4)
    analyzer = MythrilAnalyzer([contract], cfg)
    report = analyzer.fire_lasers()
    assert analyzer.sym is not None
    assert len(analyzer.sym.tx_contexts) == 2  # creation + 1 message tx
    assert report.contract_name == "Owned"


def test_analyze_jsonv2(capsys):
    rc, out = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-steps", "64", "--lanes-per-contract", "4",
        "--limits-profile", "test",
        "-m", "AccidentallyKillable", "-o", "jsonv2",
    )
    assert rc == 0
    doc = json.loads(out)
    assert isinstance(doc, list) and doc[0]["sourceType"] == "raw-bytecode"
    issues = doc[0]["issues"]
    assert issues and issues[0]["swcID"] == "SWC-106"
    assert "head" in issues[0]["description"]
    assert issues[0]["locations"][0]["sourceMap"].count(":") == 2


# --- round-4 command completeness (VERDICT r3 ask #7) ---

def test_function_to_hash(capsys):
    rc, out = run_cli(capsys, "function-to-hash", "transfer(address,uint256)")
    assert rc == 0 and out.strip() == "0xa9059cbb"


def test_hash_to_address(capsys):
    rc, out = run_cli(
        capsys, "hash-to-address",
        "0x0000000000000000000000005aaeb6053f3e94c9b9a09f33669435e7ef1beaed")
    # EIP-55 reference vector
    assert rc == 0
    assert out.strip() == "0x5aAeb6053F3E94C9b9A09f33669435E7Ef1BeAed"


def _write_rpc_mock(tmp_path, addr: str, code_hex: str, storage=None):
    mock = {addr: {"code": "0x" + code_hex,
                   "storage": {hex(k): hex(v)
                               for k, v in (storage or {}).items()}}}
    p = tmp_path / "rpc.json"
    p.write_text(json.dumps(mock))
    return f"file:{p}"


def test_read_storage_via_mock_rpc(tmp_path, capsys):
    uri = _write_rpc_mock(tmp_path, "0x" + "ab" * 20, "6001", {1: 0x2A})
    rc, out = run_cli(capsys, "read-storage", "1", "0x" + "ab" * 20,
                      "--rpc", uri)
    assert rc == 0
    assert int(out.strip(), 16) == 0x2A


def test_analyze_address_via_mock_rpc(tmp_path, capsys):
    uri = _write_rpc_mock(tmp_path, "0x" + "cd" * 20, KILLABLE)
    rc, out = run_cli(capsys, "analyze", "-a", "0x" + "cd" * 20,
                      "--rpc", uri, "-o", "json", "-t", "1",
                      "--max-steps", "64", "--lanes-per-contract", "8",
                      "--limits-profile", "test", "-m",
                      "AccidentallyKillable")
    assert rc == 0
    issues = json.loads(out)["issues"]
    assert any(i["swc-id"] == "106" for i in issues)


def test_concolic_command(capsys):
    # branch on calldata word: seed takes the fallthrough; the flip must
    # produce calldata driving the taken side
    code = assemble(
        0, "CALLDATALOAD", ("ref", "set"), "JUMPI", "STOP",
        ("label", "set"), 1, 0, "SSTORE", "STOP",
    ).hex()
    rc, out = run_cli(capsys, "concolic", "-c", code,
                      "--calldata", "00" * 32,
                      "--max-steps", "64", "--limits-profile", "test")
    assert rc == 0
    flips = json.loads(out)
    assert len(flips) >= 1
    assert any(int(f["calldata"][2:66] or "0", 16) != 0 for f in flips)


def test_safe_functions(capsys):
    # two-function dispatcher: kill() SELFDESTRUCTs (flagged),
    # totalSupply() just stores (safe); both selectors are in the local
    # signature DB
    code = assemble(
        0, "CALLDATALOAD", ("push1", 224), "SHR",
        "DUP1", ("push4", 0x41C0E1B5), "EQ", ("ref", "kill"), "JUMPI",
        "DUP1", ("push4", 0x18160DDD), "EQ", ("ref", "total"), "JUMPI",
        "STOP",
        ("label", "kill"), 0, "SELFDESTRUCT",
        ("label", "total"), 1, 2, "SSTORE", "STOP",
    ).hex()
    rc, out = run_cli(capsys, "safe-functions", "-c", code,
                      "-t", "1", "--max-steps", "64",
                      "--lanes-per-contract", "8", "--limits-profile", "test")
    assert rc == 0
    assert "totalSupply()" in out, out
    assert "kill()" not in out, out


def test_analyze_sol_file_via_stub_solc(tmp_path, capsys, monkeypatch):
    """`analyze -f contract.sol` drives the solc subprocess seam
    (round 4; reference: `myth analyze contract.sol`, SURVEY §3.1)."""
    import sys as _sys

    sol = tmp_path / "k.sol"
    sol.write_text("contract K { }\n")
    stub = tmp_path / "solc"
    stub.write_text(
        f"#!{_sys.executable}\n"
        "import json, sys\n"
        "inp = json.load(sys.stdin)\n"
        "name = list(inp['sources'])[0]\n"
        "out = {'sources': {name: {'id': 0}}, 'contracts': {name: {'K': {\n"
        "  'evm': {'deployedBytecode': {'object': '%s',\n"
        "                               'sourceMap': '0:5:0:-'}}}}}}\n"
        "json.dump(out, sys.stdout)\n" % KILLABLE
    )
    stub.chmod(0o755)
    monkeypatch.setenv("MYTHRIL_SOLC", str(stub))
    rc, out = run_cli(
        capsys, "analyze", "-f", str(sol), "-t", "1",
        "--max-steps", "32", "--lanes-per-contract", "4",
        "--limits-profile", "test", "-m", "AccidentallyKillable",
        "-o", "json",
    )
    assert rc == 0
    doc = json.loads(out)
    assert any(i["swc-id"] == "106" for i in doc["issues"])


def test_analyze_sol_without_solc_fails_clearly(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("MYTHRIL_SOLC", str(tmp_path / "missing-solc"))
    sol = tmp_path / "k.sol"
    sol.write_text("contract K { }\n")
    with pytest.raises(SystemExit) as ei:
        main(["analyze", "-f", str(sol)])
    assert ei.value.code == 2


# --- round-5 reference flag parity (VERDICT r4 ask #7) ---

def test_parser_round5_parity_flags():
    p = create_parser()
    args = p.parse_args([
        "analyze", "-c", "00", "--max-depth", "64",
        "--call-depth-limit", "3", "--solver-timeout", "5000",
        "--create-timeout", "30", "--parallel-solving",
        "--unconstrained-storage", "--statespace-json", "ss.json",
    ])
    assert args.max_depth == 64
    assert args.call_depth_limit == 3
    assert args.solver_timeout == 5000
    assert args.create_timeout == 30.0
    assert args.parallel_solving is True
    assert args.unconstrained_storage is True
    assert args.statespace_json == "ss.json"


def test_parser_worker_isolation_flag():
    p = create_parser()
    args = p.parse_args(["analyze", "--corpus", "x"])
    assert args.worker_isolation == "auto"      # on under --fleet only
    args = p.parse_args(["analyze", "--corpus", "x",
                         "--worker-isolation", "on"])
    assert args.worker_isolation == "on"
    args = p.parse_args(["serve", "--worker-isolation", "off"])
    assert args.worker_isolation == "off"
    with pytest.raises(SystemExit):
        p.parse_args(["analyze", "--corpus", "x",
                      "--worker-isolation", "sometimes"])


def test_parser_serve_overload_flags():
    p = create_parser()
    args = p.parse_args(["serve"])
    assert args.tenant_rate is None and args.quota is None
    assert args.shed_depth_hi == 0.85 and args.shed_age_hi == 30.0
    assert args.shed_priority_max == 0 and args.no_shed is False
    assert args.follow is None and args.follow_poll == 2.0
    args = p.parse_args([
        "serve", "--tenant-rate", "2.5", "--tenant-burst", "16",
        "--tenant-max-inflight", "8", "--quota", "scanner=2:8:4",
        "--quota", "ops=::64", "--shed-depth-hi", "0.5",
        "--shed-age-hi", "10", "--shed-priority-max", "1",
        "--follow", "http://127.0.0.1:8545", "--follow-poll", "0.5"])
    assert args.tenant_rate == 2.5 and args.tenant_max_inflight == 8
    assert args.quota == ["scanner=2:8:4", "ops=::64"]
    assert args.shed_depth_hi == 0.5 and args.shed_priority_max == 1
    assert args.follow == "http://127.0.0.1:8545"
    assert p.parse_args(["serve", "--no-shed"]).no_shed is True


def test_flag_max_depth_overrides_max_steps(capsys):
    # --max-depth (reference name) wins over the default --max-steps
    rc, out = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-depth", "32", "--lanes-per-contract", "4",
        "--limits-profile", "test",
        "-m", "AccidentallyKillable", "-o", "json",
    )
    assert rc == 0
    assert any(i["swc-id"] == "106" for i in json.loads(out)["issues"])


def test_flag_solver_timeout_and_parallel(capsys):
    rc, out = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-steps", "32", "--lanes-per-contract", "4",
        "--limits-profile", "test", "--solver-timeout", "10000",
        "--parallel-solving",
        "-m", "AccidentallyKillable", "-o", "json",
    )
    assert rc == 0
    assert any(i["swc-id"] == "106" for i in json.loads(out)["issues"])


def test_flag_storage_conflict_errors(capsys):
    with pytest.raises(SystemExit) as ei:
        main(["analyze", "-c", KILLABLE, "--concrete-storage",
              "--unconstrained-storage"])
    assert ei.value.code == 2


def test_flag_unconstrained_storage(capsys):
    rc, out = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-steps", "32", "--lanes-per-contract", "4",
        "--limits-profile", "test", "--unconstrained-storage",
        "-m", "AccidentallyKillable", "-o", "json",
    )
    assert rc == 0
    assert any(i["swc-id"] == "106" for i in json.loads(out)["issues"])


def test_flag_call_depth_limit_reshapes_limits(capsys):
    # a different frame cap is a different compiled shape; keep it tiny
    rc, out = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-steps", "32", "--lanes-per-contract", "4",
        "--limits-profile", "test", "--call-depth-limit", "2",
        "-m", "AccidentallyKillable", "-o", "json",
    )
    assert rc == 0
    assert any(i["swc-id"] == "106" for i in json.loads(out)["issues"])


def test_flag_create_timeout_creation_still_completes():
    ctor = assemble("CALLER", 0, "SSTORE", 0, 0, "RETURN")
    runtime = assemble(0, "SLOAD", 1, "SSTORE", "STOP")
    contract = MythrilDisassembler.load_from_bytecode(
        runtime.hex(), creation_code=ctor.hex(), name="Owned")
    cfg = MythrilConfig(limits=TEST_LIMITS, spec=SymSpec(storage=False),
                        transaction_count=1, max_steps=128,
                        lanes_per_contract=4, create_timeout=300.0)
    analyzer = MythrilAnalyzer([contract], cfg)
    analyzer.fire_lasers()
    # a generous creation budget must not mark the run timed out
    assert analyzer.sym.timed_out is False
    assert len(analyzer.sym.tx_contexts) == 2


def test_statespace_json_dump(tmp_path, capsys):
    ss = tmp_path / "statespace.json"
    rc, _ = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-steps", "32", "--lanes-per-contract", "4",
        "--limits-profile", "test", "--statespace-json", str(ss),
        "-m", "AccidentallyKillable", "-o", "json",
    )
    assert rc == 0
    doc = json.loads(ss.read_text())
    assert doc["lanes"] == 4
    assert doc["transactions"] and doc["transactions"][0]["paths"]
    p0 = doc["transactions"][0]["paths"][0]
    assert {"contract", "pc", "depth", "halted", "branches"} <= set(p0)
    assert "instruction_coverage_pct" in doc


def test_concolic_trace_file_input(tmp_path, capsys):
    # reference trace-file mode (mythril/concolic/concrete_data.py ⚠unv):
    # code + seed come from the recorded trace's last step
    code = assemble(
        0, "CALLDATALOAD", ("ref", "set"), "JUMPI", "STOP",
        ("label", "set"), 1, 0, "SSTORE", "STOP",
    )
    trace = {
        "initialState": {
            "accounts": {
                "0x" + "ab" * 20: {"code": "0x" + code.hex(),
                                   "storage": {}, "balance": "0x0",
                                   "nonce": 0}
            }
        },
        "steps": [
            {"address": "0x" + "ab" * 20, "input": "0x" + "00" * 32,
             "value": "0x0", "origin": "0x" + "cd" * 20}
        ],
    }
    p = tmp_path / "trace.json"
    p.write_text(json.dumps(trace))
    rc, out = run_cli(capsys, "concolic", "--input", str(p),
                      "--max-steps", "64", "--limits-profile", "test")
    assert rc == 0
    flips = json.loads(out)
    assert len(flips) >= 1
    assert any(int(f["calldata"][2:66] or "0", 16) != 0 for f in flips)


def test_strategy_naive_random_accepted(capsys):
    rc, out = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-steps", "32", "--lanes-per-contract", "4",
        "--limits-profile", "test", "--strategy", "naive-random",
        "-m", "AccidentallyKillable", "-o", "json",
    )
    assert rc == 0
    assert any(i["swc-id"] == "106" for i in json.loads(out)["issues"])


def test_graph_html_output(tmp_path, capsys):
    # *.html -> self-contained interactive CFG page (no external
    # resources — verifiable offline); anything else stays DOT
    html_p = tmp_path / "cfg.html"
    dot_p = tmp_path / "cfg.dot"
    for p in (html_p, dot_p):
        rc, _ = run_cli(
            capsys, "analyze", "-c", KILLABLE, "-t", "1",
            "--max-steps", "32", "--lanes-per-contract", "4",
            "--limits-profile", "test", "--graph", str(p),
            "-m", "AccidentallyKillable", "-o", "json",
        )
        assert rc == 0
    html = html_p.read_text()
    assert html.startswith("<!DOCTYPE html>")
    assert '"nodes":' in html and "__DATA__" not in html
    assert "http" not in html.split("xmlns")[0]  # no external fetches
    assert dot_p.read_text().startswith("digraph")
