"""CLI + orchestration layer (VERDICT r2 ask #6).

Reference: ``tests/cmd_line_test.py`` / ``tests/test_cli_opts.py`` (⚠unv,
SURVEY.md §4 "CLI tests") — arg parsing, output formats, command flow.
Runs in-process via ``cli.main`` (a subprocess would re-pay jax startup).
"""

import json

import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.interfaces.cli import create_parser, main
from mythril_tpu.mythril import (MythrilAnalyzer, MythrilConfig,
                                 MythrilDisassembler)
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.symbolic import SymSpec

# unprotected SELFDESTRUCT — one-instruction finding, fast to analyze
KILLABLE = assemble(0, "SELFDESTRUCT").hex()


def run_cli(capsys, *argv):
    rc = main(list(argv))
    out = capsys.readouterr().out
    return rc, out


def test_version(capsys):
    rc, out = run_cli(capsys, "version")
    assert rc == 0 and out.startswith("mythril_tpu ")


def test_list_detectors(capsys):
    rc, out = run_cli(capsys, "list-detectors")
    assert rc == 0
    assert "AccidentallyKillable" in out and "SWC-106" in out
    assert len(out.strip().splitlines()) >= 15


def test_disassemble(capsys):
    rc, out = run_cli(capsys, "d", "-c", "600160020100")
    assert rc == 0
    assert "PUSH1 0x01" in out and "ADD" in out


def test_analyze_json(capsys):
    rc, out = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-steps", "32", "--lanes-per-contract", "4",
        "--limits-profile", "test",
        "-m", "AccidentallyKillable", "-o", "json",
    )
    assert rc == 0
    payload = json.loads(out)
    assert payload["success"] is True
    swcs = {i["swc-id"] for i in payload["issues"]}
    assert "106" in swcs


def test_analyze_text_from_file(tmp_path, capsys):
    f = tmp_path / "code.hex"
    f.write_text("0x" + KILLABLE)
    rc, out = run_cli(
        capsys, "a", "-f", str(f), "-t", "1", "--max-steps", "32",
        "--lanes-per-contract", "4", "--limits-profile", "test",
        "-m", "AccidentallyKillable",
    )
    assert rc == 0
    assert "Unprotected SELFDESTRUCT" in out


def test_missing_input_errors():
    with pytest.raises(SystemExit):
        main(["analyze"])


def test_parser_reference_flags():
    p = create_parser()
    args = p.parse_args([
        "analyze", "-c", "00", "-t", "3", "-m", "EtherThief,TxOrigin",
        "-o", "markdown", "--loop-bound", "2", "--execution-timeout", "10",
    ])
    assert args.transaction_count == 3
    assert args.loop_bound == 2
    assert args.execution_timeout == 10.0


def test_orchestration_creation_path():
    # MythrilAnalyzer threads creation bytecode into the creation tx
    ctor = assemble("CALLER", 0, "SSTORE", 0, 0, "RETURN")
    runtime = assemble(0, "SLOAD", 1, "SSTORE", "STOP")
    contract = MythrilDisassembler.load_from_bytecode(
        runtime.hex(), creation_code=ctor.hex(), name="Owned")
    cfg = MythrilConfig(limits=TEST_LIMITS, spec=SymSpec(storage=False),
                        transaction_count=1, max_steps=128,
                        lanes_per_contract=4)
    analyzer = MythrilAnalyzer([contract], cfg)
    report = analyzer.fire_lasers()
    assert analyzer.sym is not None
    assert len(analyzer.sym.tx_contexts) == 2  # creation + 1 message tx
    assert report.contract_name == "Owned"


def test_analyze_jsonv2(capsys):
    rc, out = run_cli(
        capsys, "analyze", "-c", KILLABLE, "-t", "1",
        "--max-steps", "64", "--lanes-per-contract", "4",
        "--limits-profile", "test",
        "-m", "AccidentallyKillable", "-o", "jsonv2",
    )
    assert rc == 0
    doc = json.loads(out)
    assert isinstance(doc, list) and doc[0]["sourceType"] == "raw-bytecode"
    issues = doc[0]["issues"]
    assert issues and issues[0]["swcID"] == "SWC-106"
    assert "head" in issues[0]["description"]
    assert issues[0]["locations"][0]["sourceMap"].count(":") == 2
