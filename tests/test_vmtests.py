"""Consensus-style VM test vectors vs the concrete interpreter.

The independent oracle (VERDICT.md round-1 weak #6): fixtures in
``tests/fixtures/vmtests.json`` were generated with machinery deliberately
disjoint from the engine (raw-byte mini-assembler + Python big-int formula
expectations — see ``tests/fixtures/gen_vmtests.py``). The whole suite
runs as ONE batched frontier — each vector is a lane — mirroring how the
reference drives the official ``ethereum/tests`` VMTests JSON through
LASER (``tests/laser/evm_testsuite`` ⚠unv, SURVEY.md §4).
"""

import json
import os

import numpy as np
import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.core import Corpus, make_env, make_frontier
from mythril_tpu.core.interpreter import run
from mythril_tpu.disassembler import ContractImage
from mythril_tpu.ops import u256

_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "vmtests.json")
with open(_FIXTURE) as fh:
    _DOC = json.load(fh)
GAS_LIMIT = _DOC["gasLimit"]  # the GAS vectors' expectations assume this
VECTORS = _DOC["tests"]
NAMES = sorted(VECTORS)


class _SuiteRun:
    """Run every vector once (one lane each), cache the final frontier."""

    def __init__(self):
        P = len(NAMES)
        L = TEST_LIMITS
        images, calldata, cd_len = [], np.zeros((P, L.calldata_bytes), np.uint8), \
            np.zeros(P, np.int32)
        for i, name in enumerate(NAMES):
            v = VECTORS[name]
            images.append(ContractImage.from_bytecode(
                bytes.fromhex(v["exec"]["code"]), L.max_code))
            data = bytes.fromhex(v["exec"].get("data", ""))
            calldata[i, : len(data)] = np.frombuffer(data, dtype=np.uint8)
            cd_len[i] = len(data)
        corpus = Corpus.from_images(images)
        f = make_frontier(
            P, L, contract_id=np.arange(P, dtype=np.int32),
            calldata=calldata, calldata_len=cd_len, gas_limit=GAS_LIMIT,
        )
        env = make_env(P)
        f = run(f, env, corpus, max_steps=64)
        self.f = f
        self.storage = []
        st_keys = np.asarray(f.st_keys)
        st_vals = np.asarray(f.st_vals)
        st_used = np.asarray(f.st_used)
        for i in range(P):
            d = {}
            for k in range(st_keys.shape[1]):
                if st_used[i, k]:
                    d[u256.to_int(st_keys[i, k])] = u256.to_int(st_vals[i, k])
            self.storage.append(d)
        self.error = np.asarray(f.error)
        self.reverted = np.asarray(f.reverted)
        self.halted = np.asarray(f.halted)
        self.retval = np.asarray(f.retval)
        self.retval_len = np.asarray(f.retval_len)


@pytest.fixture(scope="module")
def suite():
    return _SuiteRun()


@pytest.mark.parametrize("name", NAMES)
def test_vector(suite, name):
    lane = NAMES.index(name)
    expect = VECTORS[name]["expect"]
    if expect.get("error"):
        assert bool(suite.error[lane]), f"{name}: expected exceptional halt"
        return
    assert not bool(suite.error[lane]), f"{name}: unexpected error"
    if expect.get("reverted"):
        assert bool(suite.reverted[lane]), f"{name}: expected REVERT"
    else:
        assert bool(suite.halted[lane]), f"{name}: did not halt"
        assert not bool(suite.reverted[lane]), f"{name}: unexpected revert"
    # exact storage comparison (zero values filtered on both sides, since
    # an unwritten slot and a written zero are indistinguishable in the
    # EVM's post-state): spurious extra writes fail the vector too
    want = {
        int(k, 16): int(v, 16)
        for k, v in expect.get("storage", {}).items() if int(v, 16) != 0
    }
    got = {k: v for k, v in suite.storage[lane].items() if v != 0}
    assert got == want, f"{name}: storage {got} != expected {want}"
    if "out" in expect:
        n = int(suite.retval_len[lane])
        got = bytes(suite.retval[lane][:n]).hex()
        assert got == expect["out"], f"{name}: out {got} != {expect['out']}"
