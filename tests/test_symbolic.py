"""Symbolic engine tests: forking, constraints, hash-consing, pruning.

Mirrors the reference's per-opcode symbolic unit tests (hand-built
GlobalState fixtures, SURVEY.md §4) at frontier level: each scenario is a
tiny assembled program run through sym_run on a few lanes.
"""

import numpy as np
import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.core import Corpus, make_env, make_frontier, run
from mythril_tpu.disassembler import ContractImage
from mythril_tpu.disassembler.asm import abi_call, assemble, erc20_like
from mythril_tpu.ops import u256
from mythril_tpu.symbolic import (
    SymSpec, make_sym_frontier, sym_run, kill_infeasible,
)
from mythril_tpu.symbolic.ops import SymOp, WK_CALLDATA0

import jax.numpy as jnp

CONCRETE = SymSpec(calldata=False, callvalue=False, caller=False,
                   storage=False, block_env=False)


def build(code: bytes, n_lanes: int = 4, active_lanes: int = 1, **kw):
    img = ContractImage.from_bytecode(code, TEST_LIMITS.max_code)
    corpus = Corpus.from_images([img])
    active = np.zeros(n_lanes, dtype=bool)
    active[:active_lanes] = True
    sf = make_sym_frontier(n_lanes, TEST_LIMITS, active=active, **kw)
    env = make_env(n_lanes)
    return sf, env, corpus


def srun(code, spec=SymSpec(), n_lanes=4, active_lanes=1, max_steps=128,
         propagate_every=0, **kw):
    sf, env, corpus = build(code, n_lanes, active_lanes, **kw)
    return sym_run(sf, env, corpus, spec, TEST_LIMITS,
                   max_steps=max_steps, propagate_every=propagate_every)


def stack_top_int(sf, lane):
    sp = int(sf.base.sp[lane])
    return u256.to_int(np.asarray(sf.base.stack[lane, sp - 1]))


def test_concrete_program_matches_concrete_interpreter():
    # fully concrete spec: the sym engine must agree with the plain one
    code = erc20_like()
    cd = np.zeros((2, TEST_LIMITS.calldata_bytes), dtype=np.uint8)
    blob = abi_call(0xA9059CBB, 0xB0B, 0)
    cd[:, : len(blob)] = np.frombuffer(blob, dtype=np.uint8)
    cdl = np.full(2, 68, dtype=np.int32)

    img = ContractImage.from_bytecode(code, TEST_LIMITS.max_code)
    corpus = Corpus.from_images([img])
    env = make_env(2)
    f0 = make_frontier(2, TEST_LIMITS, calldata=cd, calldata_len=cdl)
    ref = run(f0, env, corpus, max_steps=128)

    sf = srun(code, CONCRETE, n_lanes=2, active_lanes=2,
              calldata=cd, calldata_len=cdl)
    out = sf.base
    assert bool(jnp.all(out.halted == ref.halted))
    assert bool(jnp.all(out.error == ref.error))
    assert bool(jnp.all(out.reverted == ref.reverted))
    assert bool(jnp.all(out.st_vals == ref.st_vals))
    assert bool(jnp.all(out.pc == ref.pc))
    # no tape growth, no constraints in fully-concrete mode
    assert int(sf.con_len[0]) == 0


def test_symbolic_jumpi_forks_both_branches():
    # if (calldata[0] != 0) -> JUMPDEST STOP else STOP
    code = assemble(0, "CALLDATALOAD", ("ref", "yes"), "JUMPI", "STOP",
                    ("label", "yes"), "STOP")
    sf = srun(code)
    active = np.asarray(sf.base.active)
    halted = np.asarray(sf.base.halted)
    assert active.sum() == 2          # original + fork
    assert halted[active].all()
    # both lanes carry one constraint on the same node, opposite signs
    lanes = np.where(active)[0]
    assert int(sf.con_len[lanes[0]]) == 1 and int(sf.con_len[lanes[1]]) == 1
    n0, n1 = int(sf.con_node[lanes[0], 0]), int(sf.con_node[lanes[1], 0])
    assert n0 == n1 != 0
    s0, s1 = bool(sf.con_sign[lanes[0], 0]), bool(sf.con_sign[lanes[1], 0])
    assert s0 != s1
    # the fork took the jump; the original fell through
    pcs = sorted(int(sf.base.pc[l]) for l in lanes)
    assert pcs[0] != pcs[1]


def test_rebranch_on_same_condition_does_not_refork():
    # branch twice on the same condition: second JUMPI must follow the
    # recorded constraint instead of forking again
    code = assemble(
        0, "CALLDATALOAD", "ISZERO", ("ref", "a"), "JUMPI",
        # path cond: calldata0 != 0
        0, "CALLDATALOAD", "ISZERO", ("ref", "b"), "JUMPI",
        "STOP",                       # reachable: second test also false
        ("label", "a"), "STOP",
        ("label", "b"), "STOP",       # unreachable from fallthrough lane
    )
    sf = srun(code)
    active = np.asarray(sf.base.active)
    assert active.sum() == 2          # one fork total, not a 3rd lane


def test_propagation_kills_infeasible_branch():
    # cond: (calldata0 >> 240) > 2^20 — impossible (shifted value < 2^16)
    code = assemble(
        0, "CALLDATALOAD", 240, "SHR", ("push4", 1 << 20), "SWAP1", "GT",
        ("ref", "impossible"), "JUMPI", "STOP",
        ("label", "impossible"), ("push1", 1), ("push1", 0), "SSTORE", "STOP",
    )
    sf = srun(code, propagate_every=2)
    active = np.asarray(sf.base.active)
    killed = np.asarray(sf.killed_infeasible)
    assert active.sum() == 1          # impossible branch pruned
    assert killed.sum() == 1
    # surviving lane never stored
    lane = int(np.where(active)[0][0])
    assert not bool(sf.base.st_written[lane].any())


def test_storage_leaf_hash_consed_and_roundtrip():
    # SLOAD(5) twice -> same symbolic leaf; SSTORE then SLOAD -> stored value
    code = assemble(
        5, "SLOAD", 5, "SLOAD",       # two loads of untouched slot 5
        "POP", "POP",
        42, 7, "SSTORE", 7, "SLOAD",  # store 42 at slot 7, load it back
        "STOP",
    )
    sf = srun(code)
    lane = 0
    assert bool(sf.base.halted[lane]) and not bool(sf.base.error[lane])
    assert stack_top_int(sf, lane) == 42
    sp = int(sf.base.sp[lane])
    assert int(sf.stack_sym[lane, sp - 1]) == 0  # concrete after store
    # the two SLOAD(5) leaves were hash-consed into one node
    ops = np.asarray(sf.tape_op[lane])
    n_storage_leaves = int(
        ((ops == int(SymOp.FREE)) & (np.asarray(sf.tape_a[lane]) == 9)).sum()
    )
    assert n_storage_leaves == 1


def test_keccak_key_storage_roundtrip():
    # store 99 at keccak(calldata word), read back through the same key
    code = assemble(
        4, "CALLDATALOAD", 0, "MSTORE",
        99,
        32, 0, "SHA3",
        "SSTORE",
        4, "CALLDATALOAD", 0, "MSTORE",
        32, 0, "SHA3",
        "SLOAD",
        "STOP",
    )
    sf = srun(code)
    lane = 0
    assert bool(sf.base.halted[lane]) and not bool(sf.base.error[lane])
    assert stack_top_int(sf, lane) == 99


def test_call_records_event_and_pushes_symbolic_retval():
    # CALL(gas, to=0xbeef, value=7, 0,0,0,0) then branch on the result
    code = assemble(
        0, 0, 0, 0, 7, 0xBEEF, ("push2", 0xFFFF), "CALL",
        ("ref", "ok"), "JUMPI", "STOP", ("label", "ok"), "STOP",
    )
    sf = srun(code)
    active = np.asarray(sf.base.active)
    assert active.sum() == 2          # retval is symbolic -> fork
    lane = int(np.where(active)[0][0])
    assert int(sf.n_calls[lane]) == 1
    assert u256.to_int(np.asarray(sf.call_to[lane, 0])) == 0xBEEF
    assert u256.to_int(np.asarray(sf.call_value[lane, 0])) == 7
    assert int(sf.call_op[lane, 0]) == 0xF1


def test_symbolic_jump_dest_recorded():
    # JUMP to a calldata-controlled destination: SWC-127 signal
    code = assemble(0, "CALLDATALOAD", "JUMP", ("label", "x"), "STOP")
    sf = srun(code)
    lane = 0
    assert int(sf.sym_jump_dest[lane]) != 0
    assert bool(sf.base.halted[lane])


def test_fork_capacity_drops_are_counted():
    # three independent symbolic branches but only 2 lanes of capacity
    code = assemble(
        0, "CALLDATALOAD", ("ref", "a"), "JUMPI",
        ("push1", 32), "CALLDATALOAD", ("ref", "b"), "JUMPI",
        "STOP",
        ("label", "a"), "STOP",
        ("label", "b"), "STOP",
    )
    sf = srun(code, n_lanes=2, active_lanes=1)
    assert int(np.asarray(sf.dropped_forks).sum()) >= 1
    assert int(np.asarray(sf.dropped_total)) >= 1


def test_extcodesize_of_unknown_address_is_symbolic():
    # isContract pattern: EXTCODESIZE(calldata arg) must be havoc (not a
    # wrong concrete 0) so both branches of the check get explored
    code = assemble(
        4, "CALLDATALOAD", "EXTCODESIZE", "ISZERO", ("ref", "eoa"), "JUMPI",
        "STOP", ("label", "eoa"), "STOP",
    )
    sf = srun(code)
    assert np.asarray(sf.base.active).sum() == 2


def test_returndata_after_call_is_symbolic():
    # RETURNDATASIZE after an external call must fork, not pin to 0
    code = assemble(
        0, 0, 0, 0, 0, 0xBEEF, ("push2", 0xFFFF), "STATICCALL", "POP",
        "RETURNDATASIZE", ("ref", "got"), "JUMPI",
        "STOP", ("label", "got"), "STOP",
    )
    sf = srun(code)
    assert np.asarray(sf.base.active).sum() == 2


def test_calldata_selector_dispatch_explores_functions():
    # the ERC-20 contract with symbolic calldata: the dispatcher must fork
    # into the function bodies (transfer path does SSTOREs)
    sf = srun(erc20_like(), n_lanes=16, max_steps=192)
    active = np.asarray(sf.base.active)
    assert active.sum() >= 4          # fallback + 3 function paths at least
    # at least one explored path wrote storage (transfer success branch)
    assert bool((np.asarray(sf.base.st_written).any(axis=1) & active).any())
    # no lane crashed the engine
    assert not bool(np.asarray(sf.base.error)[active].any())
