"""Precompile dispatch 0x1-0x9 (VERDICT r2 ask #4).

Reference: ``mythril/laser/ethereum/natives.py`` + the dispatch in
``call.py`` (⚠unv). sha256/identity/modexp compute on device; ecrecover
is an uninterpreted leaf; the rest havoc soundly.
"""

import hashlib

import numpy as np

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.core import Corpus, make_env
from mythril_tpu.core.frontier import ACCT_CONTRACT0
from mythril_tpu.disassembler import ContractImage
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.ops import u256
from mythril_tpu.symbolic import SymSpec, make_sym_frontier, sym_run

L = TEST_LIMITS


def run_one(code, n_lanes=4, max_steps=128):
    img = ContractImage.from_bytecode(code, L.max_code)
    corpus = Corpus.from_images([img])
    active = np.zeros(n_lanes, dtype=bool)
    active[0] = True
    sf = make_sym_frontier(n_lanes, L, active=active)
    env = make_env(n_lanes)
    return sym_run(sf, env, corpus, SymSpec(), L, max_steps=max_steps)


def storage_map(sf, lane=0):
    out = {}
    used = np.asarray(sf.base.st_used)
    keys = np.asarray(sf.base.st_keys)
    vals = np.asarray(sf.base.st_vals)
    for k in range(used.shape[1]):
        if used[lane, k]:
            out[u256.to_int(keys[lane, k])] = u256.to_int(vals[lane, k])
    return out


def sym_storage_map(sf, lane=0):
    out = {}
    used = np.asarray(sf.base.st_used)
    keys = np.asarray(sf.base.st_keys)
    syms = np.asarray(sf.st_val_sym)
    for k in range(used.shape[1]):
        if used[lane, k]:
            out[u256.to_int(keys[lane, k])] = int(syms[lane, k])
    return out


def call_pre(addr, args=(0, 0), ret=(0, 32)):
    """Push CALL to precompile `addr`: gas,to,value,aOff,aLen,rOff,rLen."""
    return [ret[1], ret[0], args[1], args[0], 0, addr, ("push2", 0xFFFF), "CALL"]


def test_sha256_concrete():
    # sha256 of the 32-byte word 0x...2a stored at memory 0
    code = assemble(
        42, 0, "MSTORE",
        *call_pre(2, args=(0, 32), ret=(32, 32)),
        1, "SSTORE",            # success flag
        32, "MLOAD", 2, "SSTORE", "STOP",
    )
    out = run_one(code)
    st = storage_map(out)
    assert st[1] == 1
    expected = int.from_bytes(
        hashlib.sha256((42).to_bytes(32, "big")).digest(), "big")
    assert st[2] == expected


def test_identity_copies_bytes():
    code = assemble(
        0x1234, 0, "MSTORE",
        *call_pre(4, args=(0, 32), ret=(64, 32)),
        "POP", 64, "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code)
    assert storage_map(out)[1] == 0x1234


def test_modexp_small_operands():
    # 3 ** 5 mod 100 = 43; header lengths 32/32/32, operands at 96/128/160
    code = assemble(
        32, 0, "MSTORE", 32, 32, "MSTORE", 32, 64, "MSTORE",
        3, 96, "MSTORE", 5, 128, "MSTORE", 100, 160, "MSTORE",
        *call_pre(5, args=(0, 192), ret=(192, 32)),
        "POP", ("push1", 192), "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code, max_steps=128)
    assert storage_map(out)[1] == 43


def test_ecrecover_symbolic_input_is_leaf():
    # SYMBOLIC signature bytes: the result must be an uninterpreted leaf
    # (round 4 computes CONCRETE inputs for real — see the vector test)
    code = assemble(
        0, "CALLDATALOAD", 0, "MSTORE",   # symbolic word into the window
        *call_pre(1, args=(0, 128), ret=(0, 32)),
        "POP", 0, "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code)
    sym = sym_storage_map(out)
    assert sym[1] != 0, "ecrecover result must be an uninterpreted leaf"


def test_ecrecover_concrete_invalid_returns_empty():
    # all-zero signature: the precompile returns EMPTY output; the
    # output word stays concrete zero (VERDICT r3 weak #6)
    code = assemble(
        *call_pre(1, args=(0, 128), ret=(0, 32)),
        "POP", 0, "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code)
    assert storage_map(out)[1] == 0
    assert sym_storage_map(out)[1] == 0, "invalid recovery must be concrete"


# the canonical ethereum/tests CallEcrecover0 vector
_ECR_HASH = 0x456E9AEA5E197A1F1AF7A3E85A3212FA4049A3BA34C2289B4C860FC0B0C64EF3
_ECR_V = 28
_ECR_R = 0x9242685BF161793CC25603C231BC2F568EB630EA16AA137D2664AC8038825608
_ECR_S = 0x4F8AE3BD7535248D0BD448298CC2E2071E56992D0774DC340C368AE950852ADA
_ECR_ADDR = 0x7156526FBD7A3C72969B54F64E42C10FBB768C8A


def test_ecrecover_host_vector():
    from mythril_tpu.ops.secp256k1 import ecrecover

    assert ecrecover(_ECR_HASH, _ECR_V, _ECR_R, _ECR_S) == _ECR_ADDR
    assert ecrecover(_ECR_HASH, 29, _ECR_R, _ECR_S) is None
    assert ecrecover(_ECR_HASH, _ECR_V, 0, _ECR_S) is None


def test_ecrecover_concrete_vector_on_device():
    # the engine's concrete path recovers the signer address end-to-end
    code = assemble(
        ("push32", _ECR_HASH), 0, "MSTORE",
        _ECR_V, 32, "MSTORE",
        ("push32", _ECR_R), 64, "MSTORE",
        ("push32", _ECR_S), 96, "MSTORE",
        *call_pre(1, args=(0, 128), ret=(128, 32)),
        1, "SSTORE",
        ("push1", 128), "MLOAD", 2, "SSTORE", "STOP",
    )
    out = run_one(code)
    st = storage_map(out)
    assert st[1] == 1
    assert st[2] == _ECR_ADDR, hex(st.get(2, 0))


def test_ripemd_symbolic_input_havocs():
    # 0x3 with SYMBOLIC input bytes: success=1, result unconstrained —
    # the branch on the output must explore both sides (concrete inputs
    # compute for real below)
    code = assemble(
        0, "CALLDATALOAD", 0, "MSTORE",
        *call_pre(3, args=(0, 32), ret=(0, 32)),
        "POP", 0, "MLOAD", ("ref", "nz"), "JUMPI",
        1, 0, "SSTORE", "STOP",
        ("label", "nz"), 2, 0, "SSTORE", "STOP",
    )
    out = run_one(code, n_lanes=8)
    act = np.asarray(out.base.active)
    vals = {storage_map(out, i).get(0) for i in range(act.shape[0]) if act[i]}
    assert vals == {1, 2}


# --- round-4: the remaining natives compute concretely (ripemd160,
# alt_bn128 add/mul/pairing, blake2f) -------------------------------------


def test_blake2_f_matches_hashlib():
    # full BLAKE2b rebuilt on our F == hashlib.blake2b — external oracle
    # for the compression function the precompile exposes
    from mythril_tpu.ops.blake2 import blake2b_hash

    for msg in (b"", b"abc", b"a" * 128, b"xyz" * 100, bytes(range(129))):
        assert blake2b_hash(msg) == hashlib.blake2b(msg).digest(), msg


def test_blake2f_precompile_bytes():
    from mythril_tpu.ops.blake2 import IV, blake2f_precompile

    # single-block blake2b("abc") expressed as one F call (the EIP-152
    # vector-5 shape): h = param-tweaked IV, m = "abc" padded, t = 3
    h = list(IV)
    h[0] ^= 0x01010040
    inp = (
        (12).to_bytes(4, "big")
        + b"".join(x.to_bytes(8, "little") for x in h)
        + b"abc".ljust(128, b"\x00")
        + (3).to_bytes(8, "little") + (0).to_bytes(8, "little")
        + b"\x01"
    )
    assert blake2f_precompile(inp) == hashlib.blake2b(b"abc").digest()
    assert blake2f_precompile(inp[:-1]) is None          # bad length
    assert blake2f_precompile(inp[:-1] + b"\x02") is None  # bad final flag


def test_bn128_module():
    from mythril_tpu.ops import bn128 as bn

    assert bn.on_curve_g1(bn.G1)
    assert bn.on_curve_g2(bn.G2)
    # external anchor: the standard generators have the standard order
    assert bn._pt_mul(bn.G1, bn.CURVE_ORDER) is None
    assert bn.in_g2_subgroup(bn.G2)
    assert bn._pt_add(bn.G1, bn.G1) == bn._pt_mul(bn.G1, 2)
    # byte-level add/mul agree with the group law
    g1b = bn._write_g1(bn.G1)
    assert bn.ecadd(g1b + g1b) == bn._write_g1(bn._pt_mul(bn.G1, 2))
    assert bn.ecmul(g1b + (5).to_bytes(32, "big")) == bn._write_g1(
        bn._pt_mul(bn.G1, 5))
    # invalid points fail
    assert bn.ecadd(b"\x00" * 31 + b"\x01" + b"\x00" * 31 + b"\x01"
                    + b"\x00" * 64) is None
    assert bn.ecmul(bytes(32) + (1).to_bytes(32, "big")
                    + (1).to_bytes(32, "big")) is None


def test_bn128_pairing_bilinear():
    from mythril_tpu.ops import bn128 as bn

    e1 = bn.pairing(bn.G1, bn.G2)
    assert e1 != bn.Fq12.one(), "pairing must be non-degenerate"
    e2 = bn.pairing(bn._pt_mul(bn.G1, 2), bn.G2)
    assert e2 == e1 * e1, "bilinearity in the G1 slot"
    # the product-check shape the precompile actually runs
    assert bn.pairing_check([(bn.G1, bn.G2), (bn._pt_neg(bn.G1), bn.G2)])
    assert not bn.pairing_check([(bn.G1, bn.G2), (bn.G1, bn.G2)])


def _g2_calldata(pt) -> bytes:
    x, y = pt
    return (x.c1.to_bytes(32, "big") + x.c0.to_bytes(32, "big")
            + y.c1.to_bytes(32, "big") + y.c0.to_bytes(32, "big"))


def _mstore_words(data: bytes, base: int = 0):
    """Assembler ops writing `data` to memory word-by-word from `base`."""
    ops = []
    for i in range(0, len(data), 32):
        w = int.from_bytes(data[i:i + 32].ljust(32, b"\x00"), "big")
        ops += [("push32", w), base + i, "MSTORE"]
    return ops


def test_ripemd_concrete_on_device():
    code = assemble(
        42, 0, "MSTORE",
        *call_pre(3, args=(0, 32), ret=(32, 32)),
        1, "SSTORE",
        32, "MLOAD", 2, "SSTORE", "STOP",
    )
    out = run_one(code)
    st = storage_map(out)
    assert st[1] == 1
    digest = hashlib.new("ripemd160", (42).to_bytes(32, "big")).digest()
    assert st[2] == int.from_bytes(digest, "big")
    assert sym_storage_map(out)[2] == 0, "concrete result must stay concrete"


def test_bn128_add_concrete_on_device():
    from mythril_tpu.ops import bn128 as bn

    g1b = bn._write_g1(bn.G1)
    expected = bn.ecadd(g1b + g1b)
    code = assemble(
        *_mstore_words(g1b + g1b),
        *call_pre(6, args=(0, 128), ret=(128, 64)),
        1, "SSTORE",
        ("push1", 128), "MLOAD", 2, "SSTORE",
        ("push1", 160), "MLOAD", 3, "SSTORE", "STOP",
    )
    out = run_one(code, max_steps=128)
    st = storage_map(out)
    assert st[1] == 1
    assert st[2] == int.from_bytes(expected[:32], "big")
    assert st[3] == int.from_bytes(expected[32:], "big")


def test_bn128_invalid_point_fails_call():
    # (1, 1) is not on the curve: the CALL itself must fail (success=0,
    # empty returndata) — the one precompile-failure channel the EVM has
    code = assemble(
        1, 0, "MSTORE", 1, 32, "MSTORE",
        *call_pre(6, args=(0, 128), ret=(128, 64)),
        1, "SSTORE",
        "RETURNDATASIZE", 2, "SSTORE",
        ("push1", 128), "MLOAD", 3, "SSTORE", "STOP",
    )
    out = run_one(code, max_steps=128)
    st = storage_map(out)
    assert st[1] == 0, "invalid input must fail the precompile call"
    assert st[2] == 0 and st[3] == 0


def test_bn128_pairing_concrete_on_device():
    from mythril_tpu.ops import bn128 as bn

    g1b = bn._write_g1(bn.G1)
    neg = bn._write_g1(bn._pt_neg(bn.G1))
    g2b = _g2_calldata(bn.G2)
    inp = g1b + g2b + neg + g2b  # e(P,Q) * e(-P,Q) == 1
    code = assemble(
        *_mstore_words(inp),
        *call_pre(8, args=(0, len(inp)), ret=(384, 32)),
        1, "SSTORE",
        ("push2", 384), "MLOAD", 2, "SSTORE", "STOP",
    )
    out = run_one(code, max_steps=160)
    st = storage_map(out)
    assert st[1] == 1
    assert st[2] == 1, "pairing product must verify"


def test_blake2f_concrete_on_device():
    from mythril_tpu.ops.blake2 import IV, blake2f_precompile

    h = list(IV)
    h[0] ^= 0x01010040
    inp = (
        (12).to_bytes(4, "big")
        + b"".join(x.to_bytes(8, "little") for x in h)
        + b"abc".ljust(128, b"\x00")
        + (3).to_bytes(8, "little") + (0).to_bytes(8, "little")
        + b"\x01"
    )
    expected = blake2f_precompile(inp)
    code = assemble(
        *_mstore_words(inp),  # trailing pad bytes beyond 213 are ignored
        *call_pre(9, args=(0, 213), ret=(224, 64)),
        1, "SSTORE",
        ("push1", 224), "MLOAD", 2, "SSTORE",
        ("push2", 256), "MLOAD", 3, "SSTORE", "STOP",
    )
    out = run_one(code, max_steps=160)
    st = storage_map(out)
    assert st[1] == 1
    assert st[2] == int.from_bytes(expected[:32], "big")
    assert st[3] == int.from_bytes(expected[32:], "big")
