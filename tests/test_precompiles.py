"""Precompile dispatch 0x1-0x9 (VERDICT r2 ask #4).

Reference: ``mythril/laser/ethereum/natives.py`` + the dispatch in
``call.py`` (⚠unv). sha256/identity/modexp compute on device; ecrecover
is an uninterpreted leaf; the rest havoc soundly.
"""

import hashlib

import numpy as np

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.core import Corpus, make_env
from mythril_tpu.core.frontier import ACCT_CONTRACT0
from mythril_tpu.disassembler import ContractImage
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.ops import u256
from mythril_tpu.symbolic import SymSpec, make_sym_frontier, sym_run

L = TEST_LIMITS


def run_one(code, n_lanes=4, max_steps=128):
    img = ContractImage.from_bytecode(code, L.max_code)
    corpus = Corpus.from_images([img])
    active = np.zeros(n_lanes, dtype=bool)
    active[0] = True
    sf = make_sym_frontier(n_lanes, L, active=active)
    env = make_env(n_lanes)
    return sym_run(sf, env, corpus, SymSpec(), L, max_steps=max_steps)


def storage_map(sf, lane=0):
    out = {}
    used = np.asarray(sf.base.st_used)
    keys = np.asarray(sf.base.st_keys)
    vals = np.asarray(sf.base.st_vals)
    for k in range(used.shape[1]):
        if used[lane, k]:
            out[u256.to_int(keys[lane, k])] = u256.to_int(vals[lane, k])
    return out


def sym_storage_map(sf, lane=0):
    out = {}
    used = np.asarray(sf.base.st_used)
    keys = np.asarray(sf.base.st_keys)
    syms = np.asarray(sf.st_val_sym)
    for k in range(used.shape[1]):
        if used[lane, k]:
            out[u256.to_int(keys[lane, k])] = int(syms[lane, k])
    return out


def call_pre(addr, args=(0, 0), ret=(0, 32)):
    """Push CALL to precompile `addr`: gas,to,value,aOff,aLen,rOff,rLen."""
    return [ret[1], ret[0], args[1], args[0], 0, addr, ("push2", 0xFFFF), "CALL"]


def test_sha256_concrete():
    # sha256 of the 32-byte word 0x...2a stored at memory 0
    code = assemble(
        42, 0, "MSTORE",
        *call_pre(2, args=(0, 32), ret=(32, 32)),
        1, "SSTORE",            # success flag
        32, "MLOAD", 2, "SSTORE", "STOP",
    )
    out = run_one(code)
    st = storage_map(out)
    assert st[1] == 1
    expected = int.from_bytes(
        hashlib.sha256((42).to_bytes(32, "big")).digest(), "big")
    assert st[2] == expected


def test_identity_copies_bytes():
    code = assemble(
        0x1234, 0, "MSTORE",
        *call_pre(4, args=(0, 32), ret=(64, 32)),
        "POP", 64, "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code)
    assert storage_map(out)[1] == 0x1234


def test_modexp_small_operands():
    # 3 ** 5 mod 100 = 43; header lengths 32/32/32, operands at 96/128/160
    code = assemble(
        32, 0, "MSTORE", 32, 32, "MSTORE", 32, 64, "MSTORE",
        3, 96, "MSTORE", 5, 128, "MSTORE", 100, 160, "MSTORE",
        *call_pre(5, args=(0, 192), ret=(192, 32)),
        "POP", ("push1", 192), "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code, max_steps=128)
    assert storage_map(out)[1] == 43


def test_ecrecover_is_symbolic_leaf():
    # store the ecrecover output word: must be a tape leaf, not concrete 0
    code = assemble(
        *call_pre(1, args=(0, 128), ret=(0, 32)),
        "POP", 0, "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code)
    sym = sym_storage_map(out)
    assert sym[1] != 0, "ecrecover result must be an uninterpreted leaf"


def test_ripemd_and_bn128_havoc_success():
    # 0x3 (ripemd160): success=1, result unconstrained — the branch on the
    # output must explore both sides
    code = assemble(
        *call_pre(3, args=(0, 32), ret=(0, 32)),
        "POP", 0, "MLOAD", ("ref", "nz"), "JUMPI",
        1, 0, "SSTORE", "STOP",
        ("label", "nz"), 2, 0, "SSTORE", "STOP",
    )
    out = run_one(code, n_lanes=8)
    act = np.asarray(out.base.active)
    vals = {storage_map(out, i).get(0) for i in range(act.shape[0]) if act[i]}
    assert vals == {1, 2}
