"""Precompile dispatch 0x1-0x9 (VERDICT r2 ask #4).

Reference: ``mythril/laser/ethereum/natives.py`` + the dispatch in
``call.py`` (⚠unv). sha256/identity/modexp compute on device; ecrecover
is an uninterpreted leaf; the rest havoc soundly.
"""

import hashlib

import numpy as np

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.core import Corpus, make_env
from mythril_tpu.core.frontier import ACCT_CONTRACT0
from mythril_tpu.disassembler import ContractImage
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.ops import u256
from mythril_tpu.symbolic import SymSpec, make_sym_frontier, sym_run

L = TEST_LIMITS


def run_one(code, n_lanes=4, max_steps=128):
    img = ContractImage.from_bytecode(code, L.max_code)
    corpus = Corpus.from_images([img])
    active = np.zeros(n_lanes, dtype=bool)
    active[0] = True
    sf = make_sym_frontier(n_lanes, L, active=active)
    env = make_env(n_lanes)
    return sym_run(sf, env, corpus, SymSpec(), L, max_steps=max_steps)


def storage_map(sf, lane=0):
    out = {}
    used = np.asarray(sf.base.st_used)
    keys = np.asarray(sf.base.st_keys)
    vals = np.asarray(sf.base.st_vals)
    for k in range(used.shape[1]):
        if used[lane, k]:
            out[u256.to_int(keys[lane, k])] = u256.to_int(vals[lane, k])
    return out


def sym_storage_map(sf, lane=0):
    out = {}
    used = np.asarray(sf.base.st_used)
    keys = np.asarray(sf.base.st_keys)
    syms = np.asarray(sf.st_val_sym)
    for k in range(used.shape[1]):
        if used[lane, k]:
            out[u256.to_int(keys[lane, k])] = int(syms[lane, k])
    return out


def call_pre(addr, args=(0, 0), ret=(0, 32)):
    """Push CALL to precompile `addr`: gas,to,value,aOff,aLen,rOff,rLen."""
    return [ret[1], ret[0], args[1], args[0], 0, addr, ("push2", 0xFFFF), "CALL"]


def test_sha256_concrete():
    # sha256 of the 32-byte word 0x...2a stored at memory 0
    code = assemble(
        42, 0, "MSTORE",
        *call_pre(2, args=(0, 32), ret=(32, 32)),
        1, "SSTORE",            # success flag
        32, "MLOAD", 2, "SSTORE", "STOP",
    )
    out = run_one(code)
    st = storage_map(out)
    assert st[1] == 1
    expected = int.from_bytes(
        hashlib.sha256((42).to_bytes(32, "big")).digest(), "big")
    assert st[2] == expected


def test_identity_copies_bytes():
    code = assemble(
        0x1234, 0, "MSTORE",
        *call_pre(4, args=(0, 32), ret=(64, 32)),
        "POP", 64, "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code)
    assert storage_map(out)[1] == 0x1234


def test_modexp_small_operands():
    # 3 ** 5 mod 100 = 43; header lengths 32/32/32, operands at 96/128/160
    code = assemble(
        32, 0, "MSTORE", 32, 32, "MSTORE", 32, 64, "MSTORE",
        3, 96, "MSTORE", 5, 128, "MSTORE", 100, 160, "MSTORE",
        *call_pre(5, args=(0, 192), ret=(192, 32)),
        "POP", ("push1", 192), "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code, max_steps=128)
    assert storage_map(out)[1] == 43


def test_ecrecover_symbolic_input_is_leaf():
    # SYMBOLIC signature bytes: the result must be an uninterpreted leaf
    # (round 4 computes CONCRETE inputs for real — see the vector test)
    code = assemble(
        0, "CALLDATALOAD", 0, "MSTORE",   # symbolic word into the window
        *call_pre(1, args=(0, 128), ret=(0, 32)),
        "POP", 0, "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code)
    sym = sym_storage_map(out)
    assert sym[1] != 0, "ecrecover result must be an uninterpreted leaf"


def test_ecrecover_concrete_invalid_returns_empty():
    # all-zero signature: the precompile returns EMPTY output; the
    # output word stays concrete zero (VERDICT r3 weak #6)
    code = assemble(
        *call_pre(1, args=(0, 128), ret=(0, 32)),
        "POP", 0, "MLOAD", 1, "SSTORE", "STOP",
    )
    out = run_one(code)
    assert storage_map(out)[1] == 0
    assert sym_storage_map(out)[1] == 0, "invalid recovery must be concrete"


# the canonical ethereum/tests CallEcrecover0 vector
_ECR_HASH = 0x456E9AEA5E197A1F1AF7A3E85A3212FA4049A3BA34C2289B4C860FC0B0C64EF3
_ECR_V = 28
_ECR_R = 0x9242685BF161793CC25603C231BC2F568EB630EA16AA137D2664AC8038825608
_ECR_S = 0x4F8AE3BD7535248D0BD448298CC2E2071E56992D0774DC340C368AE950852ADA
_ECR_ADDR = 0x7156526FBD7A3C72969B54F64E42C10FBB768C8A


def test_ecrecover_host_vector():
    from mythril_tpu.ops.secp256k1 import ecrecover

    assert ecrecover(_ECR_HASH, _ECR_V, _ECR_R, _ECR_S) == _ECR_ADDR
    assert ecrecover(_ECR_HASH, 29, _ECR_R, _ECR_S) is None
    assert ecrecover(_ECR_HASH, _ECR_V, 0, _ECR_S) is None


def test_ecrecover_concrete_vector_on_device():
    # the engine's concrete path recovers the signer address end-to-end
    code = assemble(
        ("push32", _ECR_HASH), 0, "MSTORE",
        _ECR_V, 32, "MSTORE",
        ("push32", _ECR_R), 64, "MSTORE",
        ("push32", _ECR_S), 96, "MSTORE",
        *call_pre(1, args=(0, 128), ret=(128, 32)),
        1, "SSTORE",
        ("push1", 128), "MLOAD", 2, "SSTORE", "STOP",
    )
    out = run_one(code)
    st = storage_map(out)
    assert st[1] == 1
    assert st[2] == _ECR_ADDR, hex(st.get(2, 0))


def test_ripemd_and_bn128_havoc_success():
    # 0x3 (ripemd160): success=1, result unconstrained — the branch on the
    # output must explore both sides
    code = assemble(
        *call_pre(3, args=(0, 32), ret=(0, 32)),
        "POP", 0, "MLOAD", ("ref", "nz"), "JUMPI",
        1, 0, "SSTORE", "STOP",
        ("label", "nz"), 2, 0, "SSTORE", "STOP",
    )
    out = run_one(code, n_lanes=8)
    act = np.asarray(out.base.active)
    vals = {storage_map(out, i).get(0) for i in range(act.shape[0]) if act[i]}
    assert vals == {1, 2}
