"""Always-on analysis service (docs/serving.md): admission queue,
bytecode-hash dedupe, warm-compile reuse, streaming results, graceful
shutdown.

Most tests drive the REAL HTTP surface against an in-process daemon
with a stub campaign (fast, deterministic, gate-controlled); the
end-to-end test runs the real engine and asserts the acceptance
criteria: identical issues across duplicate submissions, the second
served from the dedupe store without touching a lane
(``serve_dedupe_hits_total``), and a same-shape distinct contract
skipping recompilation (``serve_warm_compile_hits_total`` up,
``engine_compiles_total`` flat).
"""

import json
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.obs import metrics as obs_metrics
from mythril_tpu.serve import (AdmissionQueue, AnalysisDaemon,
                               QueueClosed, QueueFull, ResultsStore,
                               ServeOptions)
from mythril_tpu.serve.store import bytecode_hash, config_hash

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))
import serve_client  # noqa: E402

KILLABLE = assemble(0, "SELFDESTRUCT")
SAFE = assemble(1, 0, "SSTORE", "STOP")
#: stub protocol: \x01-prefixed code -> one issue, \x02 -> quarantined
ISSUE_CODE = b"\x01" + bytes([7])
CLEAN_CODE = b"\x00" + bytes([7])
POISON_CODE = b"\x02" + bytes([7])


def counter(name):
    return obs_metrics.REGISTRY.counter(name).value


@pytest.fixture(autouse=True)
def _restore_registry_enabled():
    # the daemon force-enables the process-global registry for
    # /metrics; later suites must see the state they started with
    was = obs_metrics.REGISTRY.enabled
    yield
    obs_metrics.REGISTRY.enabled = was


class StubCampaign:
    """Resident-campaign stand-in: instant verdicts from code-byte
    markers, an optional gate that holds a batch in flight, and a
    record of every batch's names in execution order."""

    def __init__(self, gate=None):
        self.gate = gate
        self.calls = 0
        self.batches = []

    def shape_is_warm(self):
        return self.calls > 0

    def run_external_batch(self, items, bi=None):
        if self.gate is not None:
            assert self.gate.wait(30.0), "test gate never released"
        bi = self.calls
        self.calls += 1
        self.batches.append([n for n, _ in items])
        issues = [{"contract": n, "swc-id": "106", "title": "stub"}
                  for n, c in items if c.startswith(b"\x01")]
        quarantined = [{"name": n, "reason": "stub poison", "batch": bi}
                       for n, c in items if c.startswith(b"\x02")]
        return {"issues": issues, "paths": len(items), "dropped": 0,
                "iprof": {}, "quarantined": quarantined, "retries": 0,
                "status": "ok", "batch": bi, "wall_sec": 0.0}


@pytest.fixture
def daemon_factory(tmp_path):
    daemons = []

    def make(stub=None, data_dir=None, **kw):
        kw.setdefault("options", ServeOptions(batch_size=4))
        kw.setdefault("drain_timeout", 10.0)
        factory = (lambda cfg: stub) if stub is not None else None
        dm = AnalysisDaemon(
            data_dir=str(data_dir or tmp_path / "serve_data"),
            port=0, campaign_factory=factory, **kw)
        dm.start()
        daemons.append(dm)
        return dm, f"http://127.0.0.1:{dm.port}"

    yield make
    for dm in daemons:
        dm.scheduler.abort()
        dm.shutdown("test teardown")


# --- store / hashing units ---------------------------------------------

def test_store_roundtrip_and_corruption(tmp_path):
    st = ResultsStore(str(tmp_path / "store"))
    bch = bytecode_hash(ISSUE_CODE)
    cfh = config_hash({"max_steps": 64})
    assert st.get(bch, cfh) is None
    st.put(bch, cfh, {"status": "ok", "issues": [{"contract": "a"}]})
    doc = st.get(bch, cfh)
    assert doc["issues"] == [{"contract": "a"}]
    assert st.count() == 1
    # torn write -> miss, not an exception
    p = os.path.join(str(tmp_path / "store"), f"{bch}.{cfh}.json")
    with open(p, "w") as fh:
        fh.write('{"half')
    assert st.get(bch, cfh) is None


def test_config_hash_ignores_operational_knobs():
    base = {"max_steps": 64, "modules": ["AccidentallyKillable"]}
    assert config_hash(base) == config_hash(
        dict(base, fault_inject="hang:batch=1", batch_timeout=5.0,
             max_batch_retries=3, oom_ladder=("cpu",),
             solver_workers=4))
    assert config_hash(base) != config_hash(dict(base, max_steps=128))


def test_serve_options_rejects_unknown_override():
    with pytest.raises(ValueError, match="not overridable"):
        ServeOptions().effective({"lanes_per_contract": 4})
    cfg = ServeOptions(max_steps=256).effective({"max_steps": 64})
    assert cfg["max_steps"] == 64


# --- queue units --------------------------------------------------------

def test_queue_priority_and_deadline_ordering():
    q = AdmissionQueue(store=None, dedupe=False, max_depth=16)
    codes = {n: n.encode() for n in ("low", "hi", "mid_late",
                                     "mid_soon")}
    q.submit([("low", codes["low"])], priority=0)
    q.submit([("mid_late", codes["mid_late"])], priority=5,
             deadline_sec=60.0)
    q.submit([("mid_soon", codes["mid_soon"])], priority=5,
             deadline_sec=5.0)
    q.submit([("hi", codes["hi"])], priority=9)
    order = []
    while q.depth():
        batch = q.pop_batch(1, timeout=0.1)
        order.extend(e.name for e in batch)
        for e in batch:
            q.resolve(e, {"status": "ok", "issues": []})
    # higher priority first; earlier deadline breaks the tie; FIFO last
    assert order == ["hi", "mid_soon", "mid_late", "low"]


def test_queue_full_and_closed():
    q = AdmissionQueue(store=None, dedupe=False, max_depth=2)
    q.submit([("a", b"\x00a"), ("b", b"\x00b")])
    with pytest.raises(QueueFull):
        q.submit([("c", b"\x00c")])
    q.close()
    with pytest.raises(QueueClosed):
        q.submit([("d", b"\x00d")])


def test_queue_inflight_dedupe_within_submission(tmp_path):
    st = ResultsStore(str(tmp_path / "store"))
    q = AdmissionQueue(store=st, dedupe=True, max_depth=16)
    hits0 = counter("serve_dedupe_hits_total")
    sub = q.submit([("orig", ISSUE_CODE), ("clone1", ISSUE_CODE),
                    ("clone2", ISSUE_CODE)])
    # one primary queued, two followers attached — nothing reaches a
    # second lane slot
    assert q.depth() == 1
    assert counter("serve_dedupe_hits_total") - hits0 == 2
    (e,) = q.pop_batch(4, timeout=0.1)
    q.resolve(e, {"status": "ok",
                  "issues": [{"contract": e.name, "swc-id": "106"}]})
    assert sub.done
    names = sorted(r["name"] for r in sub.results)
    assert names == ["clone1", "clone2", "orig"]
    # every result carries the issue, re-homed onto its own name
    for r in sub.results:
        assert [i["contract"] for i in r["issues"]] == [r["name"]]
    assert sorted(r.get("served_from", "analysis")
                  for r in sub.results) == [
        "analysis", "dedupe-inflight", "dedupe-inflight"]


# --- HTTP layer (stub campaign) -----------------------------------------

def _submit(url, contracts, **kw):
    return serve_client.submit(url, contracts, **kw)


def test_http_submit_result_and_dedupe_store(daemon_factory):
    stub = StubCampaign()
    dm, url = daemon_factory(stub=stub)
    hits0 = counter("serve_dedupe_hits_total")
    snap = _submit(url, [("k", ISSUE_CODE), ("s", CLEAN_CODE)])
    res = serve_client.get_result(url, snap["id"], wait=20.0)
    assert res["state"] == "done"
    by = {r["name"]: r for r in res["results"]}
    assert len(by["k"]["issues"]) == 1 and by["s"]["issues"] == []
    assert stub.calls == 1
    # resubmit: both verdicts in the store now — no batch runs
    snap2 = _submit(url, [("k2", ISSUE_CODE), ("s2", CLEAN_CODE)])
    assert snap2["state"] == "done"   # resolved at admission
    assert all(r["served_from"] == "dedupe-store"
               for r in snap2["results"])
    assert [i["contract"] for r in snap2["results"]
            for i in r["issues"]] == ["k2"]
    assert stub.calls == 1
    assert counter("serve_dedupe_hits_total") - hits0 == 2


def test_http_streaming_matches_commit_order(daemon_factory):
    # batch_size=1 -> one commit per contract, FIFO within a priority:
    # the chunked stream must yield exactly that order
    stub = StubCampaign()
    dm, url = daemon_factory(stub=stub,
                             options=ServeOptions(batch_size=1))
    names = [f"c{i}" for i in range(5)]
    contracts = [(n, b"\x01" + n.encode()) for n in names]
    snap = _submit(url, contracts)
    got = []
    for rec in serve_client.stream_results(url, snap["id"],
                                           timeout=30.0):
        if rec.get("done"):
            assert rec["completed"] == len(names)
            break
        got.append(rec["name"])
    # the engine saw unique per-entry names; strip the entry suffix
    assert got == names == [b[0].split("@")[0] for b in stub.batches]


def test_http_concurrent_submitters_inflight_dedupe(daemon_factory):
    gate = threading.Event()
    stub = StubCampaign(gate=gate)
    dm, url = daemon_factory(stub=stub)
    hits0 = counter("serve_dedupe_hits_total")
    sids, errs = [], []

    def one(k):
        try:
            sids.append(_submit(
                url, [(f"t{k}", ISSUE_CODE)], tenant=f"t{k}")["id"])
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=one, args=(k,))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert not errs and len(sids) == 4
    gate.set()
    outs = [serve_client.get_result(url, sid, wait=20.0)
            for sid in sids]
    assert all(o["state"] == "done" for o in outs)
    assert all(len(o["results"][0]["issues"]) == 1 for o in outs)
    # one analysis total; the other three submissions were followers
    assert stub.calls == 1
    assert counter("serve_dedupe_hits_total") - hits0 == 3


def test_http_deadline_eviction(daemon_factory):
    gate = threading.Event()
    stub = StubCampaign(gate=gate)
    dm, url = daemon_factory(stub=stub)
    ev0 = counter("serve_evicted_total")
    # first submission occupies the scheduler (gate held)...
    s1 = _submit(url, [("busy", b"\x01busy")])
    time.sleep(0.1)
    # ...so this one's deadline lapses while QUEUED
    s2 = _submit(url, [("late", b"\x01late")], deadline_sec=0.05)
    time.sleep(0.2)
    gate.set()
    out = serve_client.get_result(url, s2["id"], wait=20.0)
    assert out["state"] == "done"
    assert out["results"][0]["status"] == "evicted"
    assert counter("serve_evicted_total") - ev0 == 1
    busy = serve_client.get_result(url, s1["id"], wait=20.0)
    assert busy["results"][0]["status"] == "ok"


def test_http_queue_full_429(daemon_factory):
    # shed disabled: this test pins the BOUNDED-QUEUE contract (the
    # shed ladder would otherwise answer the overflow degraded with a
    # 202 — that path has its own tests in test_serve_overload.py)
    gate = threading.Event()
    stub = StubCampaign(gate=gate)
    dm, url = daemon_factory(stub=stub, max_queue=1, shed=None,
                             options=ServeOptions(batch_size=1))
    _submit(url, [("a", b"\x01aa")])          # popped -> running
    deadline = time.monotonic() + 5.0
    while dm.queue.depth() == 0 and time.monotonic() < deadline:
        time.sleep(0.01)                       # wait for the pop
    _submit(url, [("b", b"\x01bb")])          # queued (depth 1)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _submit(url, [("c", b"\x01cc")])
    assert exc.value.code == 429
    gate.set()


def test_http_metrics_prometheus_text(daemon_factory):
    stub = StubCampaign()
    dm, url = daemon_factory(stub=stub)
    _submit(url, [("k", ISSUE_CODE)])
    text = serve_client.metrics(url)
    line_re = re.compile(
        r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ?.*"
        r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE.+-]+)$")
    lines = [ln for ln in text.splitlines() if ln]
    assert lines, "empty /metrics"
    for ln in lines:
        assert line_re.match(ln), f"bad prometheus line: {ln!r}"
    assert "mythril_serve_requests_total" in text


def test_http_bad_requests(daemon_factory):
    stub = StubCampaign()
    dm, url = daemon_factory(stub=stub)
    for body in (b"{}", b"not json", b'{"contracts": []}',
                 b'{"code": "zz"}',
                 b'{"code": "00", "options": {"lanes_per_contract": 1}}'):
        req = urllib.request.Request(
            f"{url}/v1/submit", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(f"{url}/v1/result/sXXX", timeout=10)
    assert exc.value.code == 404


def test_graceful_drain_503_and_exactly_once_restart(tmp_path,
                                                     daemon_factory):
    """SIGTERM semantics without the signal plumbing: during the drain
    new submissions get 503 and /healthz says draining; the in-flight
    batch finishes and persists; a restarted daemon on the same data
    dir serves the finished verdicts from the store (exactly once) and
    analyzes only what never committed."""
    gate = threading.Event()
    stub = StubCampaign(gate=gate)
    data_dir = tmp_path / "sdata"
    dm, url = daemon_factory(stub=stub, data_dir=data_dir,
                             options=ServeOptions(batch_size=1),
                             drain_timeout=20.0)
    s1 = _submit(url, [("done1", ISSUE_CODE)])
    gate.set()
    assert serve_client.get_result(url, s1["id"],
                                   wait=20.0)["state"] == "done"
    gate.clear()
    s2 = _submit(url, [("inflight", b"\x01if"), ("queued", b"\x01qq")])
    # batch_size=1: the scheduler pops 'inflight' (now held by the
    # gate) and 'queued' stays queued — wait for that split
    deadline = time.monotonic() + 5.0
    while dm.queue.depth() != 1:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    # drain on a helper thread (it blocks on the gated batch)
    t = threading.Thread(target=dm.shutdown, args=("test",))
    t.start()
    deadline = time.monotonic() + 5.0
    while dm.state != "draining" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert serve_client.healthz(url)["state"] == "draining"
    with pytest.raises(urllib.error.HTTPError) as exc:
        _submit(url, [("rejected", b"\x01no")])
    assert exc.value.code == 503
    gate.set()          # in-flight batch completes during the drain
    t.join(30.0)
    assert dm.state == "stopped"
    assert s2["contracts"] == 2
    # restart on the same data dir with a FRESH stub: the committed
    # verdicts (done1, inflight) must come from the store; 'queued'
    # was failed by the drain and must re-analyze
    stub2 = StubCampaign()
    dm2, url2 = daemon_factory(stub=stub2, data_dir=data_dir)
    snap = _submit(url2, [("done1", ISSUE_CODE), ("inflight", b"\x01if"),
                          ("queued", b"\x01qq")])
    out = serve_client.get_result(url2, snap["id"], wait=20.0)
    assert out["state"] == "done"
    by = {r["name"]: r for r in out["results"]}
    assert by["done1"]["served_from"] == "dedupe-store"
    assert by["inflight"]["served_from"] == "dedupe-store"
    assert "served_from" not in by["queued"]
    assert [[n.split("@")[0] for n in b]
            for b in stub2.batches] == [["queued"]]  # only the lost work
    for r in by.values():
        assert len(r["issues"]) == 1       # same verdicts, exactly once


def test_quarantined_contract_not_cached(daemon_factory):
    stub = StubCampaign()
    dm, url = daemon_factory(stub=stub)
    snap = _submit(url, [("bad", POISON_CODE)])
    out = serve_client.get_result(url, snap["id"], wait=20.0)
    assert out["results"][0]["status"] == "quarantined"
    assert "stub poison" in out["results"][0]["error"]
    # poison verdicts are NOT stored: a resubmit re-analyzes
    snap2 = _submit(url, [("bad2", POISON_CODE)])
    out2 = serve_client.get_result(url, snap2["id"], wait=20.0)
    assert out2["results"][0]["status"] == "quarantined"
    assert stub.calls == 2


# --- fleet-fed mode -----------------------------------------------------

def test_fleet_feed_daemon_and_follow_worker(tmp_path, daemon_factory):
    """The daemon fronts a fleet: admitted batches land in a FEED
    ledger, a --fleet-follow worker (here: an in-process campaign with
    a stub runner) claims and commits them, and the results stream
    back through the same resolution path."""
    from mythril_tpu.mythril.campaign import CorpusCampaign

    fleet = str(tmp_path / "feed")
    dm, url = daemon_factory(stub=None, fleet_dir=fleet,
                             options=ServeOptions(batch_size=2))

    def runner(bi, names, codes):
        return {"issues": [{"contract": n, "swc-id": "106"}
                           for n, c in zip(names, codes)
                           if c.startswith(b"\x01")],
                "paths": len(names), "dropped": 0, "iprof": {}}

    worker = CorpusCampaign(
        [], batch_size=2, fleet_dir=fleet, fleet_follow=True,
        lease_ttl=2.0, worker_id="w-test", batch_runner=runner,
        execution_timeout=60.0)
    wres = {}

    def run_worker():
        wres["res"] = worker.run()

    wt = threading.Thread(target=run_worker)
    wt.start()
    try:
        snap = _submit(url, [("k", b"\x01k1"), ("s", b"\x00s1")])
        out = serve_client.get_result(url, snap["id"], wait=30.0)
        assert out["state"] == "done"
        by = {r["name"]: r for r in out["results"]}
        assert len(by["k"]["issues"]) == 1 and by["s"]["issues"] == []
        assert by["k"]["issues"][0]["contract"] == "k"
    finally:
        dm.shutdown("test")    # closes the feed -> worker drains out
        wt.join(30.0)
    assert not wt.is_alive()
    assert wres["res"].fleet["units"], "worker committed no units"
    assert wres["res"].contracts == 2


# --- end-to-end with the real engine ------------------------------------

def test_e2e_dedupe_and_warm_compile_real_engine(tmp_path):
    """The acceptance path (ISSUE 7): same contract twice -> identical
    issues, the second from the dedupe store with no batch run; a
    distinct same-shape contract -> analyzed WITHOUT recompiling
    (warm-compile hit; engine compile counter flat)."""
    opts = ServeOptions(batch_size=2, lanes_per_contract=8,
                        max_steps=64, transaction_count=1,
                        modules=["AccidentallyKillable"],
                        limits_profile="test")
    dm = AnalysisDaemon(opts, data_dir=str(tmp_path / "sd"), port=0)
    dm.start()
    url = f"http://127.0.0.1:{dm.port}"
    try:
        k1 = assemble(0, "SELFDESTRUCT")
        k2 = assemble(2, "SELFDESTRUCT")     # distinct code, same shape
        hits0 = counter("serve_dedupe_hits_total")
        warm0 = counter("serve_warm_compile_hits_total")

        first = serve_client.get_result(
            url, _submit(url, [("orig", k1)])["id"], wait=300.0)
        assert first["state"] == "done"
        (r1,) = first["results"]
        assert r1["status"] == "ok" and len(r1["issues"]) == 1
        assert r1["issues"][0]["contract"] == "orig"
        batches_after_first = dm.scheduler.batches_run

        # 1) duplicate bytecode: served from the store, no lane touched
        second = serve_client.get_result(
            url, _submit(url, [("dup", k1)])["id"], wait=30.0)
        (r2,) = second["results"]
        assert r2["served_from"] == "dedupe-store"
        assert counter("serve_dedupe_hits_total") - hits0 == 1
        assert dm.scheduler.batches_run == batches_after_first
        # identical issues (modulo the display name they re-home to)
        strip = (lambda i: {k: v for k, v in i.items()
                            if k != "contract"})
        assert ([strip(i) for i in r2["issues"]]
                == [strip(i) for i in r1["issues"]])

        # 2) same-shape distinct contract: no recompile
        compiles0 = counter("engine_compiles_total")
        third = serve_client.get_result(
            url, _submit(url, [("fresh", k2)])["id"], wait=300.0)
        (r3,) = third["results"]
        assert r3["status"] == "ok" and len(r3["issues"]) == 1
        assert "served_from" not in r3
        assert counter("serve_warm_compile_hits_total") - warm0 >= 1
        assert counter("engine_compiles_total") == compiles0
    finally:
        dm.shutdown("test")
    assert dm.state == "stopped"


# --- cross-process request tracing (docs/observability.md) --------------

def _get_trace(url, tid):
    with urllib.request.urlopen(f"{url}/v1/trace/{tid}",
                                timeout=30.0) as resp:
        return json.load(resp)


def test_e2e_cross_process_trace_with_worker_kill(tmp_path):
    """The distributed-tracing acceptance path (ISSUE 14): with worker
    isolation ON, one trace_id stitches HTTP submit -> admission ->
    scheduler -> campaign batch -> worker-subprocess spans (backhauled
    over the batch IPC, clock-corrected) -> verdict commit into ONE
    monotone timeline served by /v1/trace — including a worker KILLED
    mid-batch, whose undelivered span buffer is declared lost
    (worker_telemetry_lost) before the retry's fresh worker ships the
    replay's telemetry. Per-result timings sum to the request wall."""
    from mythril_tpu.config import TEST_LIMITS
    from mythril_tpu.mythril.campaign import CorpusCampaign
    from mythril_tpu.resilience import (FaultInjector, FaultSpec,
                                        WorkerSupervisor)

    from mythril_tpu.obs import trace as obs_trace
    assert not obs_trace.active()      # the daemon must own the tracer

    inj = FaultInjector([FaultSpec.parse("worker-kill:nth=1")])
    sup = WorkerSupervisor(stub=True, batch_timeout=30.0,
                           backoff_base=0.01, spawn_timeout=60.0,
                           fault_injector=inj)
    camp = CorpusCampaign([], limits=TEST_LIMITS, batch_size=4,
                          lanes_per_contract=4, max_steps=16,
                          worker_isolation="on", worker_supervisor=sup,
                          fault_injector=inj)
    lost0 = counter("engine_worker_telemetry_lost_total")
    dm = AnalysisDaemon(data_dir=str(tmp_path / "sd"), port=0,
                        options=ServeOptions(batch_size=4),
                        campaign_factory=lambda cfg: camp)
    dm.start()
    url = f"http://127.0.0.1:{dm.port}"
    try:
        assert obs_trace.active()      # auto-tracer without --trace
        snap = _submit(url, [("a", b"\x00aa"), ("b", b"\x00bb")])
        res = serve_client.get_result(url, snap["id"], wait=60.0)
        assert res["state"] == "done"

        # the batch survived the mid-batch worker kill via retry, and
        # the first worker's undelivered telemetry was DECLARED lost
        assert counter("engine_worker_telemetry_lost_total") - lost0 >= 1

        # one trace id for the whole submission, echoed per result
        assert res["trace_id"]
        tid = res["trace_id"]
        assert all(r["trace_id"] == tid for r in res["results"])

        doc = _get_trace(url, tid)
        assert doc["trace_id"] == tid and doc["spans"] >= 3
        recs = doc["records"]
        # every record of the stitched view belongs to this trace
        assert all(r.get("trace_id") == tid
                   or tid in (r.get("trace_ids") or ()) for r in recs)
        # ... in ONE monotone timeline
        monos = [r["mono"] for r in recs]
        assert monos == sorted(monos)
        # ... spanning >= 2 processes: the daemon's own records plus
        # worker-subprocess spans backhauled over the batch IPC
        worker = [r for r in recs if r.get("proc") == "worker"]
        assert worker, "no worker-side records in the stitched trace"
        parent_sessions = {r["session"] for r in recs}
        assert all(r["src_session"] not in parent_sessions
                   for r in worker)
        assert any(r.get("name") == "device_phase" for r in worker)
        names = {r.get("name") for r in recs if r["kind"] == "span"}
        kinds = {r["kind"] for r in recs}
        assert {"admit", "queue_wait", "schedule"} <= names
        assert "verdict_commit" in kinds
        assert "worker_telemetry_lost" in kinds   # the kill, declared

        # per-stage attribution: stages sum to the request wall
        for r in res["results"]:
            tm = r["timings"]
            assert set(tm) >= {"admission", "sched_wait", "device",
                               "commit", "total"}
            stages = sum(v for k, v in tm.items() if k != "total")
            assert abs(stages - tm["total"]) <= max(
                0.10 * tm["total"], 0.05), tm
        # the end-to-end histogram powering the heartbeat's req token
        rh = obs_metrics.REGISTRY.histogram("serve_request_seconds")
        assert rh.count >= 2 and rh.quantile(0.95) is not None

        # an unknown id is a 404, not an empty timeline
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_trace(url, "0" * 16)
        assert ei.value.code == 404
    finally:
        dm.shutdown("test")
        sup.close()
    assert not obs_trace.active()      # daemon closed its own tracer


# --- scheduler crash containment (docs/resilience.md) -------------------

def test_scheduler_crash_fails_pending_and_degrades_health(
        daemon_factory, monkeypatch):
    """If the scheduler loop thread dies of an unhandled error,
    pending requests fail IMMEDIATELY (they used to hang until their
    deadlines), /healthz flips to degraded with the error string, and
    new submissions get 503."""
    gate = threading.Event()
    started = threading.Event()

    class TrackedStub(StubCampaign):
        def run_external_batch(self, items, bi=None):
            started.set()
            return super().run_external_batch(items, bi)

    stub = TrackedStub(gate=gate)
    dm, url = daemon_factory(stub=stub)
    # batch A occupies the scheduler (gate held) ...
    snap_a = _submit(url, [("a", ISSUE_CODE)])
    assert started.wait(20.0)
    # ... B queues behind it; the crash is armed for the NEXT pop
    snap_b = _submit(url, [("b", b"\x01" + bytes([8]))])
    monkeypatch.setattr(
        dm.queue, "pop_batch",
        lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("chaos: scheduler eats it")))
    gate.set()  # A completes; the loop's next pop dies
    out_a = serve_client.get_result(url, snap_a["id"], wait=20.0)
    assert out_a["results"][0]["status"] == "ok"   # in-flight work landed
    out_b = serve_client.get_result(url, snap_b["id"], wait=20.0)
    assert out_b["state"] == "done"                # failed FAST, no hang
    (r,) = out_b["results"]
    assert r["status"] == "error"
    assert "scheduler loop died" in r["error"]
    assert "chaos: scheduler eats it" in r["error"]
    health = serve_client.healthz(url)
    assert health["state"] == "degraded" and health["ok"] is False
    assert "chaos: scheduler eats it" in health["error"]
    assert dm.scheduler.crashed
    # the queue closed with the crash: new submissions 503 fast
    with pytest.raises(urllib.error.HTTPError) as ei:
        _submit(url, [("late", CLEAN_CODE)])
    assert ei.value.code == 503


def test_healthz_reports_degraded_worker_configs(daemon_factory):
    """An open engine-worker crash-loop breaker surfaces per config in
    /healthz degraded_configs while the daemon keeps serving."""
    stub = StubCampaign()
    dm, url = daemon_factory(stub=stub)

    class _BrokenWorkerCampaign:
        def worker_status(self):
            return {"breaker": "open", "deaths_in_window": 3,
                    "restarts": 5, "alive": False}

    dm.scheduler._campaigns["cfh-broken"] = _BrokenWorkerCampaign()
    health = serve_client.healthz(url)
    assert health["state"] == "serving"     # still serving other work
    (dc,) = health["degraded_configs"]
    assert dc["config"] == "cfh-broken" and dc["breaker"] == "open"
    assert dc["restarts"] == 5
    assert health["engine_worker_restarts"] == 5
    # the daemon still answers real work alongside the degraded config
    snap = _submit(url, [("ok", ISSUE_CODE)])
    out = serve_client.get_result(url, snap["id"], wait=20.0)
    assert out["results"][0]["status"] == "ok"


# --- client retry (tools/serve_client.py) --------------------------------

def test_client_with_retry_connection_errors(monkeypatch):
    monkeypatch.setattr(serve_client.time, "sleep", lambda s: None)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise urllib.error.URLError(ConnectionRefusedError(111))
        return {"ok": True}

    assert serve_client.with_retry(flaky, retries=3) == {"ok": True}
    assert len(calls) == 3
    # exhausted budget raises the live error
    calls.clear()
    with pytest.raises(urllib.error.URLError):
        serve_client.with_retry(flaky, retries=1)
    assert len(calls) == 2


def test_client_with_retry_503_drain_only(monkeypatch):
    monkeypatch.setattr(serve_client.time, "sleep", lambda s: None)

    def http_err(code):
        return urllib.error.HTTPError("u", code, "x", {}, None)

    calls = []

    def draining():
        calls.append(1)
        if len(calls) < 2:
            raise http_err(503)
        return {"ok": True}

    assert serve_client.with_retry(draining, retries=2) == {"ok": True}
    # 4xx is the CALLER's bug: never retried
    calls.clear()

    def bad_request():
        calls.append(1)
        raise http_err(400)

    with pytest.raises(urllib.error.HTTPError):
        serve_client.with_retry(bad_request, retries=5)
    assert len(calls) == 1
    # retries=0 is the legacy fail-fast contract
    calls.clear()

    def down():
        calls.append(1)
        raise http_err(503)

    with pytest.raises(urllib.error.HTTPError):
        serve_client.with_retry(down, retries=0)
    assert len(calls) == 1


def test_client_retry_rides_daemon_restart(tmp_path):
    """The restart story end to end: submit to a live daemon, kill it,
    then a get_result with retries spans the gap to a restarted daemon
    on the SAME port serving from the dedupe store."""
    data_dir = str(tmp_path / "restart_data")
    stub = StubCampaign()
    dm = AnalysisDaemon(data_dir=data_dir, port=0,
                        campaign_factory=lambda cfg: stub,
                        options=ServeOptions(batch_size=4))
    dm.start()
    port = dm.port
    url = f"http://127.0.0.1:{port}"
    snap = _submit(url, [("k", ISSUE_CODE)])
    out = serve_client.get_result(url, snap["id"], wait=20.0)
    assert out["state"] == "done"
    dm.shutdown("test restart")

    result = {}

    def client():
        # the daemon is DOWN when this starts: only the retry loop
        # (connection refused -> backoff -> reconnect) can succeed
        result["snap"] = serve_client.submit(
            url, [("k2", ISSUE_CODE)], retries=8, backoff=0.1)

    t = threading.Thread(target=client)
    t.start()
    time.sleep(0.3)
    dm2 = AnalysisDaemon(data_dir=data_dir, port=port,
                         campaign_factory=lambda cfg: StubCampaign(),
                         options=ServeOptions(batch_size=4))
    dm2.start()
    try:
        t.join(20.0)
        assert not t.is_alive()
        snap2 = result["snap"]
        # same bytecode+config: served straight from the durable store
        assert snap2["completed"] == 1
        assert snap2["results"][0]["served_from"] == "dedupe-store"
    finally:
        dm2.shutdown("test")
