"""In-jit cross-block lane migration (``migrate_parked_device``).

The ICI tier of SURVEY §5.8's cross-device rebalancing: starved
fork-requesting lanes move between blocks INSIDE the jitted superstep
loop through a compact per-block payload buffer, with no host seam.
The host-planned ``rebalance_parked`` keeps the chunk-boundary tier;
these tests pin the device tier's semantics and its GSPMD compatibility
on the virtual 8-device mesh (conftest).
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.core import Corpus, make_env
from mythril_tpu.disassembler import ContractImage
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.symbolic import (SymSpec, make_sym_frontier,
                                  migrate_parked_device, sym_run)

L = TEST_LIMITS
P = 32
B = 4  # 8 blocks of 4 lanes


def synth(active_mask, parked_mask):
    """Frontier with per-lane pc = lane index (a movement tracer)."""
    sf = make_sym_frontier(P, L, active=np.asarray(active_mask))
    return sf.replace(
        base=sf.base.replace(pc=jnp.arange(P, dtype=jnp.int32)),
        fork_req=jnp.asarray(parked_mask),
    )


def test_starved_lane_moves_to_freest_block():
    active = np.zeros(P, dtype=bool)
    active[0:4] = True          # block 0 exhausted
    active[4:6] = True          # block 1: 2 free slots
    # block 2..7 empty: 4 free slots each -> freest, fills first
    parked = np.zeros(P, dtype=bool)
    parked[1] = True            # starved lane, tracer pc = 1
    sf = synth(active, parked)

    out = jax.jit(migrate_parked_device, static_argnums=(1,))(sf, B)
    act = np.asarray(out.base.active)
    pc = np.asarray(out.base.pc)
    req = np.asarray(out.fork_req)

    assert not act[1] and not req[1]          # vacated
    moved = np.where(act & (pc == 1))[0]
    assert moved.size == 1                     # exactly one copy
    assert moved[0] >= 8                       # landed in an empty block
    assert req[moved[0]]                       # still parked -> will retry
    assert act.sum() == active.sum()           # lane count conserved


def test_noop_when_own_block_has_free_slot():
    active = np.zeros(P, dtype=bool)
    active[0:3] = True          # block 0 has one free slot
    parked = np.zeros(P, dtype=bool)
    parked[1] = True
    sf = synth(active, parked)

    out = jax.jit(migrate_parked_device, static_argnums=(1,))(sf, B)
    np.testing.assert_array_equal(np.asarray(out.base.active), active)
    np.testing.assert_array_equal(np.asarray(out.fork_req), parked)
    np.testing.assert_array_equal(np.asarray(out.base.pc), np.arange(P))


def test_capacity_bounded_rest_stay_parked():
    active = np.ones(P, dtype=bool)
    active[28:32] = False       # only block 7 has room (4 free)
    parked = np.zeros(P, dtype=bool)
    parked[0:4] = True          # block 0: four starved lanes
    sf = synth(active, parked)

    out = jax.jit(migrate_parked_device, static_argnums=(1,))(sf, B)
    act = np.asarray(out.base.active)
    req = np.asarray(out.fork_req)
    # cap = min(free-1, MIG=B//2) = min(3, 2) = 2 migrants accepted
    assert act.sum() == active.sum()
    assert (act[28:32] & (np.asarray(out.base.pc)[28:32] < 4)).sum() == 2
    assert req.sum() == 4                      # none lost: moved OR parked


def test_iprof_rows_conserved_across_migration():
    active = np.zeros(P, dtype=bool)
    active[0:4] = True
    parked = np.zeros(P, dtype=bool)
    parked[2] = True
    sf = synth(active, parked)
    hist = jnp.zeros((P, 256), jnp.int32).at[2, 0x57].set(7).at[9, 0x01].set(3)
    sf = sf.replace(base=sf.base.replace(op_hist=hist))  # lane 9: dead counts

    out = jax.jit(migrate_parked_device, static_argnums=(1,))(sf, B)
    oh = np.asarray(out.base.op_hist)
    assert oh.sum() == 10                      # harvest totals conserved
    moved = np.where(np.asarray(out.base.active)
                     & (np.asarray(out.base.pc) == 2))[0]
    assert oh[moved[0], 0x57] == 7             # counts travelled with it


def test_iprof_residual_sidecar_keeps_rows_attributable():
    """With the sidecar attached (what ``attach_iprof`` now does), a
    replaced slot's unharvested counts land in ``op_resid`` instead of
    being folded into an arbitrary live lane's row (ADVICE r5) — the
    per-lane histogram stays attributable while harvest totals
    (rows + sidecar) are conserved."""
    active = np.zeros(P, dtype=bool)
    active[0:4] = True
    parked = np.zeros(P, dtype=bool)
    parked[2] = True
    sf = synth(active, parked)
    # lane 4 = first free slot of the freest block (block 1 — all empty
    # blocks tie, stable sort picks the lowest) = the import slot the
    # migrant lands in; its row holds a retired lane's unharvested counts
    hist = jnp.zeros((P, 256), jnp.int32).at[2, 0x57].set(7).at[4, 0x01].set(3)
    sf = sf.replace(base=sf.base.replace(
        op_hist=hist, op_resid=jnp.zeros(256, jnp.int32)))

    out = jax.jit(migrate_parked_device, static_argnums=(1,))(sf, B)
    oh = np.asarray(out.base.op_hist)
    resid = np.asarray(out.base.op_resid)
    moved = np.where(np.asarray(out.base.active)
                     & (np.asarray(out.base.pc) == 2))[0]
    assert moved.size == 1
    assert oh[moved[0], 0x57] == 7     # counts travelled with the lane
    assert resid[0x01] == 3            # orphaned row -> sidecar, not a lane
    assert resid.sum() == 3
    assert oh.sum() == 7               # no live row absorbed foreign counts
    assert oh.sum() + resid.sum() == 10  # harvest total conserved


def test_sharded_migration_matches_unsharded():
    active = np.zeros(P, dtype=bool)
    active[0:4] = True
    active[4:6] = True
    parked = np.zeros(P, dtype=bool)
    parked[0] = parked[3] = True
    sf = synth(active, parked)

    ref = jax.jit(migrate_parked_device, static_argnums=(1,))(sf, B)

    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, axis_names=("dp",))

    def shard_leaf(x):
        if hasattr(x, "shape") and x.ndim >= 1 and x.shape[0] == P:
            return NamedSharding(mesh, PS("dp", *([None] * (x.ndim - 1))))
        return NamedSharding(mesh, PS())

    sh = jax.tree.map(shard_leaf, sf)
    out = jax.jit(migrate_parked_device, static_argnums=(1,),
                  in_shardings=(sh,), out_shardings=sh)(
        jax.device_put(sf, sh), B)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# end-to-end: seeds crowded into one block starve without migration;
# with it they spread into the empty blocks and finish more paths
CODE = assemble(
    0, "CALLDATALOAD", ("ref", "a"), "JUMPI",
    1, 0, "SSTORE",
    4, "CALLDATALOAD", ("ref", "b"), "JUMPI",
    2, 1, "SSTORE", "STOP",
    ("label", "a"), 3, 0, "SSTORE", "STOP",
    ("label", "b"), 4, 1, "SSTORE", "STOP",
)


def _run(migrate_every):
    img = ContractImage.from_bytecode(CODE, L.max_code)
    corpus = Corpus.from_images([img])
    active = np.zeros(P, dtype=bool)
    active[0:4] = True          # block 0 full; blocks 1..7 empty
    sf = make_sym_frontier(P, L, active=active)
    env = make_env(P)
    return sym_run(sf, env, corpus, SymSpec(), L, max_steps=64,
                   fork_block=B, defer_starved=True,
                   migrate_every=migrate_every)


def test_sym_run_migration_unblocks_starved_forks():
    stuck = _run(0)
    moved = _run(1)
    done_stuck = int(np.asarray(stuck.base.halted & ~stuck.base.error).sum())
    done_moved = int(np.asarray(moved.base.halted & ~moved.base.error).sum())
    assert done_moved > done_stuck             # migration freed real work
    # nothing dropped in either mode (defer_starved retries, never drops)
    assert int(np.asarray(moved.dropped_total)) == 0
    # migrated run explores every path of the 2-branch fixture: 4 leaves
    assert done_moved >= 4
