"""Remote 4byte.directory tier of the SignatureDB (VERDICT r4 missing
#5), loopback-tested like the RPC client: a threaded local HTTP server
plays 4byte.directory's /api/v1/signatures/ endpoint shape."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from mythril_tpu.utils.signatures import SignatureDB, selector_of

KNOWN = "lockAndLoad(uint256,bytes32)"  # NOT in the built-in table
KNOWN_SEL = selector_of(KNOWN)


class _FourByte(BaseHTTPRequestHandler):
    requests = None  # list of hex_signature params seen

    def do_GET(self):  # noqa: N802
        q = parse_qs(urlparse(self.path).query)
        sel = (q.get("hex_signature") or [""])[0]
        if type(self).requests is not None:
            type(self).requests.append(sel)
        results = ([{"id": 1, "text_signature": KNOWN}]
                   if sel == "0x" + KNOWN_SEL else [])
        data = json.dumps({"count": len(results),
                           "results": results}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


@pytest.fixture()
def fourbyte():
    _FourByte.requests = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FourByte)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}/api/v1/signatures/"
    finally:
        srv.shutdown()
        srv.server_close()


def test_remote_hit_is_memoized(fourbyte):
    db = SignatureDB(remote_url=fourbyte)
    assert db.lookup(KNOWN_SEL) == [KNOWN]
    assert db.lookup(KNOWN_SEL) == [KNOWN]  # second hit from local table
    assert len(_FourByte.requests) == 1     # exactly one remote round-trip


def test_remote_miss_is_memoized(fourbyte):
    db = SignatureDB(remote_url=fourbyte)
    missing = "deadbeef"
    assert db.lookup(missing) == []
    assert db.lookup(missing) == []
    assert len(_FourByte.requests) == 1     # miss cached, no re-query


def test_local_hit_never_queries_remote(fourbyte):
    db = SignatureDB(remote_url=fourbyte)
    assert db.lookup(selector_of("transfer(address,uint256)")) == [
        "transfer(address,uint256)"]
    assert _FourByte.requests == []


def test_dead_endpoint_degrades_to_local_only():
    db = SignatureDB(remote_url="http://127.0.0.1:1/api", remote_timeout=0.2)
    assert db.lookup("cafebabe") == []       # silent miss, no exception
    assert db.lookup(selector_of("deposit()")) == ["deposit()"]


def test_env_var_opt_in(fourbyte, monkeypatch):
    monkeypatch.setenv("MYTHRIL_4BYTE_URL", fourbyte)
    db = SignatureDB()
    assert db.lookup(KNOWN_SEL) == [KNOWN]
