"""Lost-coverage accounting: masked traps are attributed and reported.

VERDICT.md round-1 weak #4: a lane tripping a static cap must not vanish
silently — the report carries a coverage block saying what was lost.
"""

import json

import numpy as np

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.core import Corpus, make_env, make_frontier
from mythril_tpu.core.frontier import Trap
from mythril_tpu.core.interpreter import run
from mythril_tpu.disassembler import ContractImage
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.analysis import SymExecWrapper, fire_lasers


def _run_concrete(code: bytes, max_steps: int = 64):
    img = ContractImage.from_bytecode(code, TEST_LIMITS.max_code)
    corpus = Corpus.from_images([img])
    f = make_frontier(4, TEST_LIMITS)
    env = make_env(4)
    return run(f, env, corpus, max_steps=max_steps)


def test_bad_jump_trap_attributed():
    f = _run_concrete(assemble(3, "JUMP", "STOP"))
    assert bool(np.asarray(f.error).all())
    assert np.asarray(f.err_code)[0] == Trap.BAD_JUMP


def test_invalid_opcode_trap_attributed():
    f = _run_concrete(bytes([0xFE]))
    assert np.asarray(f.err_code)[0] == Trap.INVALID_OP


def test_stack_cap_trip_is_warned_in_report():
    # an unrolled push sequence deeper than TEST_LIMITS.max_stack (32)
    blower = assemble(*([1] * (TEST_LIMITS.max_stack + 4)), "STOP")
    sym = SymExecWrapper([blower], limits=TEST_LIMITS,
                         lanes_per_contract=4, max_steps=64)
    report = fire_lasers(sym)
    cov = report.coverage
    assert cov is not None
    assert cov["lanes_errored"].get("stack_cap", 0) >= 1
    assert cov["lanes_lost_to_caps"] >= 1
    assert any("capacity caps" in w for w in report.coverage_warnings())
    assert "WARNING" in report.as_text()
    assert json.loads(report.as_json())["coverage"]["lanes_lost_to_caps"] >= 1


def test_clean_run_has_no_warnings():
    clean = assemble(1, ("push1", 0), "SSTORE", "STOP")
    sym = SymExecWrapper([clean], limits=TEST_LIMITS,
                         lanes_per_contract=4, max_steps=64)
    report = fire_lasers(sym)
    assert report.coverage["lanes_lost_to_caps"] == 0
    assert report.coverage_warnings() == []
    assert report.coverage["surviving_paths"] >= 1
