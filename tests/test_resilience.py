"""Fault-isolated campaign runner (resilience layer).

The failure modes this repo has actually hit — a wedged backend that
hangs ``jax.devices()`` forever (docs/tpu-wedge-round5.md), a hung XLA
compile, a pathological contract crashing a batch — must cost a 10k
campaign at most the poison contracts, never the run. All fault paths
are exercised deterministically on CPU via the injection hook; the
tier-1 budget is respected by testing the supervisor machinery against
a stub batch runner (no engine) and reserving the real engine for one
raise-variant quarantine + kill/resume scenario that reuses the
test_campaign compiled shape.
"""

import json
import os

import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.disassembler.asm import assemble
from mythril_tpu.mythril.campaign import (CorpusCampaign, load_corpus_dir,
                                          merge_campaigns)
from mythril_tpu.resilience import (BackendManager, BatchTimeout,
                                    DeviceLostError, FaultInjector,
                                    FaultSpec, InjectedKill,
                                    ResilienceError, ResourceExhausted,
                                    classify_backend_error, parse_ladder,
                                    run_with_watchdog)
from mythril_tpu.utils.checkpoint import (CheckpointCorrupt,
                                          load_json_checkpoint)

# --- watchdog ---------------------------------------------------------


def test_watchdog_passthrough_and_timeout():
    import time

    assert run_with_watchdog(lambda: 42, None) == 42      # inline path
    assert run_with_watchdog(lambda: "ok", 5.0) == "ok"   # thread path
    with pytest.raises(BatchTimeout, match="wall-clock budget"):
        run_with_watchdog(lambda: time.sleep(30), 0.2, label="hung work")


def test_watchdog_relays_exceptions_including_base():
    def boom():
        raise ValueError("from the worker")

    with pytest.raises(ValueError, match="from the worker"):
        run_with_watchdog(boom, 5.0)

    def kill():
        raise InjectedKill("simulated SIGKILL")

    # BaseException must blow through too — a simulated kill cannot be
    # downgraded to a retryable batch failure by the watchdog seam
    with pytest.raises(InjectedKill):
        run_with_watchdog(kill, 5.0)


# --- fault specs ------------------------------------------------------


def test_fault_spec_parse_and_matching():
    s = FaultSpec.parse("raise:contract=c002:times=1")
    assert (s.mode, s.contract, s.times) == ("raise", "c002", 1)
    assert s.matches(0, ["c002", "c003"])
    assert not s.matches(0, ["c000"])
    s.fired = 1
    assert not s.matches(0, ["c002"])      # times budget spent

    b = FaultSpec.parse("hang:batch=2")
    assert b.matches(2, []) and not b.matches(1, [])

    for bad in ("explode:batch=1", "raise", "raise:frob=1", "raise:batch"):
        with pytest.raises(ValueError):
            FaultSpec.parse(bad)


def test_fault_injector_from_env(monkeypatch):
    monkeypatch.setenv("MYTHRIL_FAULT_INJECT",
                       "raise:batch=0:times=1;kill:batch=3")
    inj = FaultInjector.from_env()
    assert [s.mode for s in inj.specs] == ["raise", "kill"]
    with pytest.raises(ResilienceError):
        inj.fire(batch=0, contracts=["x"])
    inj.fire(batch=0, contracts=["x"])     # times=1: second pass clean
    with pytest.raises(InjectedKill):
        inj.fire(batch=3, contracts=[])
    assert len(inj.log) == 2
    monkeypatch.delenv("MYTHRIL_FAULT_INJECT")
    assert FaultInjector.from_env() is None


# --- backend-error classification + ladder parsing --------------------


def test_classify_backend_error():
    assert classify_backend_error(ResourceExhausted("boom")) == "oom"
    assert classify_backend_error(MemoryError()) == "oom"
    assert classify_backend_error(DeviceLostError("gone")) == "device-lost"

    class XlaRuntimeError(RuntimeError):
        """jaxlib look-alike: no stable subclasses per status code, so
        the classifier must go by the status string in the message."""

    assert classify_backend_error(XlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 2147483648 bytes"
    )) == "oom"
    assert classify_backend_error(XlaRuntimeError(
        "Execution failed: DEVICE_LOST: device poll timeout")) == "device-lost"
    assert classify_backend_error(XlaRuntimeError(
        "XLA compilation failure: invalid HLO")) == "compile"
    assert classify_backend_error(ValueError("ordinary bug")) is None
    assert classify_backend_error(RuntimeError("failed to allocate "
                                               "device buffer")) == "oom"


def test_parse_ladder():
    assert parse_ladder(None) == ("halve-lanes", "halve-batch", "cpu")
    assert parse_ladder("halve-batch,cpu") == ("halve-batch", "cpu")
    assert parse_ladder("none") == ()
    assert parse_ladder("") == ()
    with pytest.raises(ValueError, match="rung"):
        parse_ladder("halve-lanes,frobnicate")


def test_oom_fault_mode_fires_resource_exhausted():
    inj = FaultInjector.from_string("oom:batch=1:times=1")
    with pytest.raises(ResourceExhausted, match="RESOURCE_EXHAUSTED"):
        inj.fire(batch=1, contracts=[])
    inj.fire(batch=1, contracts=[])        # times budget spent
    assert [e["mode"] for e in inj.log] == ["oom"]


# --- backend manager --------------------------------------------------


def test_backend_manager_bounded_retries_and_events():
    calls = []

    def probe(timeout_s):
        calls.append(timeout_s)
        return False, "injected probe failure"

    bm = BackendManager(init_timeout=0.5, max_attempts=3, backoff=0.0,
                        probe_fn=probe)
    ok, diag = bm.probe()
    assert not ok and "injected" in diag
    assert calls == [0.5, 0.5, 0.5]        # bounded re-init attempts
    assert [e["kind"] for e in bm.events] == ["probe_fail"] * 3
    assert [e["attempt"] for e in bm.events] == [1, 2, 3]


def test_backend_manager_cpu_fallback_event(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")  # restore after test
    bm = BackendManager(init_timeout=0.1, max_attempts=1, backoff=0.0,
                        probe_fn=lambda t: (False, "wedged"))
    ok, diag = bm.ensure_or_fallback()
    assert not ok
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    assert bm.events[-1]["kind"] == "cpu_fallback"

    good = BackendManager(probe_fn=lambda t: (True, "OK cpu 1"))
    ok, diag = good.ensure_or_fallback()
    assert ok and diag == "OK cpu 1"
    assert [e["kind"] for e in good.events] == ["probe_ok"]


def test_backend_manager_recover_records_device_loss():
    bm = BackendManager(probe_fn=lambda t: (True, "OK"), backoff=0.0)
    assert bm.recover(reason="injected device loss")
    kinds = [e["kind"] for e in bm.events]
    assert kinds == ["device_lost", "probe_ok"]


def test_backend_manager_real_subprocess_probe_on_cpu():
    """The genuine probe path: a child process inits the CPU backend
    inside the deadline (the wedge case can't be reproduced on CPU; the
    timeout path is covered by probe_fn injection above)."""
    bm = BackendManager(init_timeout=120.0, max_attempts=1)
    ok, diag = bm.probe()
    assert ok, diag
    assert diag.startswith("OK")


# --- campaign supervisor against a stub runner ------------------------

N = 6
STUB_CONTRACTS = [(f"c{i:03d}", b"\x00") for i in range(N)]


def _stub_runner(bi, names, codes):
    return {"issues": [{"contract": n, "batch": bi}
                       for n in names if not n.startswith("_pad_")],
            "paths": len(names), "dropped": 0, "iprof": {}}


def stub_campaign(ckpt, fault, batch_timeout=2.0, retries=1):
    return CorpusCampaign(
        STUB_CONTRACTS, batch_size=2, checkpoint_dir=ckpt,
        spec=object(),               # stub runner never touches the spec
        batch_timeout=batch_timeout,
        max_batch_retries=retries,
        fault_injector=FaultInjector.from_string(fault),
        batch_runner=_stub_runner,
    )


def test_stub_raise_fault_quarantines_only_poison(tmp_path):
    res = stub_campaign(str(tmp_path / "a"), "raise:contract=c002").run()
    assert res.batches == 3                      # run completed
    assert res.batch_status == ["ok", "quarantined:1", "ok"]
    assert [(q["name"], q["batch"]) for q in res.quarantined] == [("c002", 1)]
    assert "ResilienceError" in res.quarantined[0]["reason"]
    # the poison's batchmate and every other batch still analyzed
    assert ({i["contract"] for i in res.issues}
            == {"c000", "c001", "c003", "c004", "c005"})
    assert res.retries == 1                      # the retry-once attempt


def test_stub_hang_fault_times_out_and_quarantines(tmp_path):
    res = stub_campaign(str(tmp_path / "h"), "hang:contract=c003",
                        batch_timeout=0.3).run()
    assert res.batches == 3
    assert [(q["name"], q["batch"]) for q in res.quarantined] == [("c003", 1)]
    assert res.quarantined[0]["reason"].startswith("timeout:")
    assert ({i["contract"] for i in res.issues}
            == {"c000", "c001", "c002", "c004", "c005"})


def test_stub_transient_fault_cured_by_retry(tmp_path):
    res = stub_campaign(str(tmp_path / "t"), "raise:batch=0:times=1").run()
    assert res.retries == 1 and not res.quarantined
    assert res.batch_status == ["ok-retry", "ok", "ok"]
    assert len(res.issues) == N                  # nothing lost


def test_stub_device_lost_triggers_backend_recovery(tmp_path):
    bm = BackendManager(probe_fn=lambda t: (True, "OK"), backoff=0.0)
    c = stub_campaign(str(tmp_path / "d"), "device-lost:batch=1:times=1")
    c.backend = bm
    res = c.run()
    assert res.batch_status[1] == "ok-retry" and res.retries == 1
    kinds = [e["kind"] for e in res.backend_events]
    assert "device_lost" in kinds and "probe_ok" in kinds


def test_stub_kill_resume_no_double_count(tmp_path):
    """Acceptance: kill mid-campaign via injected fault, resume, and the
    final issue set / contract counts / quarantine list match a straight
    faulted run — nothing double-counted, nothing silently skipped."""
    ck = str(tmp_path / "k")
    with pytest.raises(InjectedKill):
        stub_campaign(ck, "raise:contract=c002;kill:batch=2").run()
    # the kill struck AFTER batch 1 checkpointed, BEFORE batch 2 did
    state = load_json_checkpoint(os.path.join(ck, "campaign.json"))
    assert state["next_batch"] == 2
    assert [q["name"] for q in state["quarantined"]] == ["c002"]

    resumed = stub_campaign(ck, "raise:contract=c002").run()
    straight = stub_campaign(str(tmp_path / "s"),
                             "raise:contract=c002").run()
    for a, b in ((resumed, straight),):
        assert a.batches == b.batches == 3
        assert a.contracts == b.contracts == N
        assert (sorted(i["contract"] for i in a.issues)
                == sorted(i["contract"] for i in b.issues))
        assert a.quarantined == b.quarantined
    # quarantine persisted across the kill: exactly one entry, not two
    assert [q["name"] for q in resumed.quarantined] == ["c002"]


def test_stub_old_checkpoint_schema_resumes(tmp_path):
    """A pre-resilience checkpoint (no quarantined/retries/batch_status/
    backend_events keys) must resume cleanly with defaulted fields."""
    ck = str(tmp_path / "old")
    with pytest.raises(InjectedKill):
        stub_campaign(ck, "kill:batch=1").run()
    p = os.path.join(ck, "campaign.json")
    state = load_json_checkpoint(p)
    for k in ("quarantined", "retries", "batch_status", "backend_events"):
        del state[k]
    # written back as a BARE state dict — the pre-versioning (v1) JSON
    # format, so this also covers the old-format load path
    json.dump(state, open(p, "w"))
    if os.path.exists(p + ".1"):
        os.unlink(p + ".1")  # v1 runs never rotated
    res = stub_campaign(ck, None).run()
    assert res.batches == 3 and res.retries == 0
    # pre-kill batches carry no status marker in the rewound schema —
    # only the post-resume batches are re-attributed
    assert res.batch_status == ["ok", "ok"]


# --- degradation ladder (stub runner) ---------------------------------


def _degradable_stub(calls):
    """Stub runner that understands degraded capacity: records every
    (batch, n_items, lanes, width) attempt for assertions."""

    def runner(bi, names, codes, lanes=None, width=None):
        calls.append((bi, len(names), lanes, width))
        return {"issues": [{"contract": n, "batch": bi}
                           for n in names if not n.startswith("_pad_")],
                "paths": len(names), "dropped": 0, "iprof": {}}

    return runner


def degradable_campaign(ckpt, fault, calls, **kw):
    return CorpusCampaign(
        STUB_CONTRACTS, batch_size=2, checkpoint_dir=ckpt,
        spec=object(), batch_timeout=5.0,
        fault_injector=FaultInjector.from_string(fault),
        batch_runner=_degradable_stub(calls), **kw)


def test_oom_degrades_one_rung_and_completes(tmp_path):
    """Acceptance: a batch that OOMs completes after an automatic lane
    shrink — visible as backend_events ladder steps — instead of
    failing/quarantining anything."""
    calls = []
    res = degradable_campaign(str(tmp_path / "o1"),
                              "oom:batch=1:times=1", calls).run()
    assert res.batches == 3
    assert res.batch_status == ["ok", "ok-degraded:halve-lanes", "ok"]
    assert not res.quarantined and res.retries == 0
    steps = [e["step"] for e in res.backend_events
             if e["kind"] == "degrade"]
    assert steps == ["halve-lanes"]
    assert any(e["kind"] == "degrade_ok" for e in res.backend_events)
    # every contract analyzed exactly once
    assert (sorted(i["contract"] for i in res.issues)
            == [f"c{i:03d}" for i in range(N)])
    # the degraded attempt really ran with halved frontier lanes
    degraded = [c for c in calls if c[2] is not None]
    assert degraded == [(1, 2, 16, 2)]     # default 32 lanes -> 16


def test_oom_walks_ladder_cumulatively_to_halve_batch(tmp_path):
    """Two consecutive OOMs walk to the second rung: lanes stay halved
    AND the batch replays as two half-width sub-batches."""
    calls = []
    res = degradable_campaign(str(tmp_path / "o2"),
                              "oom:batch=0:times=2", calls).run()
    assert res.batch_status[0] == "ok-degraded:halve-batch"
    steps = [e["step"] for e in res.backend_events
             if e["kind"] == "degrade"]
    assert steps == ["halve-lanes", "halve-batch"]
    # the successful rung: two sub-batches of width 1, lanes still 16
    sub = [c for c in calls if c[3] == 1]
    assert sub == [(0, 1, 16, 1), (0, 1, 16, 1)]
    assert (sorted(i["contract"] for i in res.issues)
            == [f"c{i:03d}" for i in range(N)])


def test_oom_cpu_rung_and_event_trail(tmp_path):
    """Three consecutive OOMs reach the CPU rung (full ladder)."""
    calls = []
    res = degradable_campaign(str(tmp_path / "o3"),
                              "oom:batch=0:times=3", calls).run()
    # times=3: full attempt, halve-lanes, and halve-batch's FIRST
    # sub-attempt each fire (a failed rung discards partial results);
    # the cpu rung's sub-attempts run clean
    assert res.batch_status[0] == "ok-degraded:cpu"
    steps = [e["step"] for e in res.backend_events
             if e["kind"] == "degrade"]
    assert steps == ["halve-lanes", "halve-batch", "cpu"]
    assert (sorted(i["contract"] for i in res.issues)
            == [f"c{i:03d}" for i in range(N)])


def test_oom_ladder_exhausted_falls_to_quarantine(tmp_path):
    """A persistent per-contract OOM (poison, not pressure) exhausts the
    ladder and lands in the retry→bisect machinery: the run survives,
    the poison is quarantined with the RESOURCE_EXHAUSTED reason."""
    res = stub_campaign(str(tmp_path / "oq"), "oom:contract=c002").run()
    assert res.batches == 3
    assert [(q["name"], q["batch"]) for q in res.quarantined] == [("c002", 1)]
    assert "RESOURCE_EXHAUSTED" in res.quarantined[0]["reason"]
    assert res.batch_status == ["ok", "quarantined:1", "ok"]
    steps = [e["step"] for e in res.backend_events
             if e["kind"] == "degrade"]
    assert steps == ["halve-lanes", "halve-batch", "cpu"]
    assert ({i["contract"] for i in res.issues}
            == {"c000", "c001", "c003", "c004", "c005"})


def test_oom_ladder_disabled_goes_straight_to_retry(tmp_path):
    calls = []
    res = degradable_campaign(str(tmp_path / "o0"),
                              "oom:batch=1:times=1", calls,
                              oom_ladder=()).run()
    # no ladder: the transient OOM is cured by the ordinary retry
    assert res.batch_status == ["ok", "ok-retry", "ok"]
    assert res.retries == 1
    assert not [e for e in res.backend_events if e["kind"] == "degrade"]


# --- checkpoint cadence + torn-checkpoint resume ----------------------


def test_checkpoint_every_bounds_loss_no_double_count(tmp_path):
    ck = str(tmp_path / "ce")

    def mk(fault):
        return CorpusCampaign(
            STUB_CONTRACTS, batch_size=1, checkpoint_dir=ck,
            spec=object(), batch_runner=_stub_runner,
            checkpoint_every=2,
            fault_injector=FaultInjector.from_string(fault))

    with pytest.raises(InjectedKill):
        mk("kill:batch=3").run()
    # batches 0..2 ran; with N=2 cadence only batches 0-1 are durable —
    # the kill loses at most checkpoint_every batches
    state = load_json_checkpoint(os.path.join(ck, "campaign.json"))
    assert state["next_batch"] == 2
    assert len(state["issues"]) == 2
    res = mk(None).run()
    assert res.batches == N
    # batch 2's unpersisted first-attempt results died with the kill, so
    # its replay cannot double-count
    assert (sorted(i["contract"] for i in res.issues)
            == [f"c{i:03d}" for i in range(N)])


def test_torn_campaign_checkpoint_falls_back_to_rotation(tmp_path):
    """Acceptance: SIGKILL mid-checkpoint-write (simulated by truncating
    the newest checkpoint at several offsets) resumes from the rotated
    last-known-good copy, losing at most one batch, analyzing nothing
    twice."""
    ck = str(tmp_path / "torn")
    with pytest.raises(InjectedKill):
        stub_campaign(ck, "kill:batch=2").run()   # batches 0,1 durable
    p = os.path.join(ck, "campaign.json")
    raw = open(p, "rb").read()
    for cut in (0, 7, len(raw) // 2, len(raw) - 2):
        with open(p, "wb") as fh:
            fh.write(raw[:cut])
        res = stub_campaign(ck, None).run()
        assert "checkpoint_recovered" in [e["kind"]
                                          for e in res.backend_events]
        # rotated copy says next_batch=1: batch 1 replays (its results
        # were only in the discarded torn file), batch 2 runs — every
        # contract exactly once
        assert res.batches == 3
        assert (sorted(i["contract"] for i in res.issues)
                == [f"c{i:03d}" for i in range(N)])
        # restore the torn newest for the next tear shape
        with open(p, "wb") as fh:
            fh.write(raw)


def test_first_checkpoint_torn_starts_fresh(tmp_path):
    ck = str(tmp_path / "fresh")
    with pytest.raises(InjectedKill):
        stub_campaign(ck, "kill:batch=1").run()   # only batch 0 durable
    p = os.path.join(ck, "campaign.json")
    if os.path.exists(p + ".1"):
        os.unlink(p + ".1")
    with open(p, "w") as fh:
        fh.write('{"__schema__": 2, "sha256": "tor')
    res = stub_campaign(ck, None).run()
    assert res.batches == 3
    assert (sorted(i["contract"] for i in res.issues)
            == [f"c{i:03d}" for i in range(N)])
    assert "checkpoint_reset" in [e["kind"] for e in res.backend_events]


def test_merge_campaigns_carries_resilience_fields():
    r0 = {"contracts": 3, "batches": 1, "issues": 1, "wall_sec": 1.0,
          "quarantined": [{"name": "c002", "reason": "x", "batch": 0}],
          "retries": 2, "batch_status": ["quarantined:1"],
          "backend_events": [{"kind": "probe_ok"}]}
    r1 = {"contracts": 3, "batches": 1, "issues": 2, "wall_sec": 2.0,
          "quarantined": [], "retries": 0, "batch_status": ["ok"]}
    m = merge_campaigns([r0, r1])
    assert [q["name"] for q in m["quarantined"]] == ["c002"]
    assert m["retries"] == 2
    assert m["batch_status"] == ["quarantined:1", "ok"]
    assert [e["kind"] for e in m["backend_events"]] == ["probe_ok"]


# --- real engine: raise-variant quarantine + kill/resume --------------

KILLABLE = assemble(0, "SELFDESTRUCT")
SAFE = assemble(1, 0, "SSTORE", "STOP")


def write_corpus(tmp_path, n=6):
    d = tmp_path / "corpus"
    d.mkdir(exist_ok=True)
    for i in range(n):
        code = KILLABLE if i % 2 == 0 else SAFE
        (d / f"c{i:03d}.hex").write_text(code.hex())
    return str(d)


def engine_campaign(corpus_dir, ckpt=None, fault=None):
    # same shapes as tests/test_campaign.py: one compiled engine serves
    # both files' batches via the persistent compilation cache
    return CorpusCampaign(
        load_corpus_dir(corpus_dir),
        batch_size=4, lanes_per_contract=8, limits=TEST_LIMITS,
        max_steps=64, transaction_count=1,
        modules=["AccidentallyKillable"], checkpoint_dir=ckpt,
        fault_injector=FaultInjector.from_string(fault),
    )


def test_engine_fault_quarantine_kill_and_resume(tmp_path):
    """Real-engine acceptance path: poison contract c002 (itself
    killable) in batch 0 of 2, killed before batch 1 checkpoints, then
    resumed — all non-poison contracts are analyzed exactly once and
    the poison is quarantined with a reason, across the kill."""
    corpus = write_corpus(tmp_path)
    ck = str(tmp_path / "ck")
    with pytest.raises(InjectedKill):
        engine_campaign(corpus, ckpt=ck,
                        fault="raise:contract=c002;kill:batch=1").run()
    state = load_json_checkpoint(os.path.join(ck, "campaign.json"))
    assert state["next_batch"] == 1
    assert [q["name"] for q in state["quarantined"]] == ["c002"]

    resumed = engine_campaign(corpus, ckpt=ck,
                              fault="raise:contract=c002").run()
    assert resumed.batches == 2 and resumed.contracts == 6
    assert [(q["name"], q["batch"])
            for q in resumed.quarantined] == [("c002", 0)]
    assert resumed.batch_status == ["quarantined:1", "ok"]
    # killable contracts are c000/c002/c004; the quarantined poison is
    # the ONLY missing finding, and nothing is double-counted
    found = sorted(i["contract"] for i in resumed.issues)
    assert found == ["c000", "c004"], found
    assert all(i["swc-id"] == "106" for i in resumed.issues)

    # straight faulted run (no kill) reproduces the same final state
    straight = engine_campaign(corpus, ckpt=str(tmp_path / "ck2"),
                               fault="raise:contract=c002").run()
    assert straight.contracts == resumed.contracts
    assert (sorted(i["contract"] for i in straight.issues) == found)
    assert ([(q["name"], q["batch"]) for q in straight.quarantined]
            == [(q["name"], q["batch"]) for q in resumed.quarantined])


def test_cli_campaign_oom_degrade_end_to_end(tmp_path, capsys):
    """Acceptance via the CLI with the REAL engine: a batch that OOMs
    (injected) completes after the automatic lane shrink — the ladder
    step is visible in backend_events, nothing is quarantined, and the
    issue set matches an unfaulted run."""
    from mythril_tpu.interfaces.cli import main

    corpus = write_corpus(tmp_path)
    rc = main(["analyze", "--corpus", corpus, "--batch-size", "4",
               "--lanes-per-contract", "8", "--max-steps", "64",
               "--limits-profile", "test", "-t", "1",
               "-m", "AccidentallyKillable", "-o", "json",
               "--fault-inject", "oom:batch=0:times=1",
               "--oom-ladder", "halve-lanes",
               "--checkpoint-every", "2",
               "--checkpoint-dir", str(tmp_path / "ck")])
    out = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(out)
    assert payload["batch_status"][0] == "ok-degraded:halve-lanes"
    steps = [e.get("step") for e in payload["backend_events"]
             if e["kind"] == "degrade"]
    assert steps == ["halve-lanes"]
    assert not payload["quarantined"]
    # the degraded (4-lane) replay still finds every killable contract
    assert ({i["contract"] for i in payload["issues_detail"]}
            == {"c000", "c002", "c004"})


def test_cli_campaign_fault_flags(tmp_path, capsys):
    """--fault-inject / --batch-timeout / --max-batch-retries thread
    through the CLI into the campaign; the JSON report carries the
    quarantine."""
    from mythril_tpu.interfaces.cli import main

    corpus = write_corpus(tmp_path)
    rc = main(["analyze", "--corpus", corpus, "--batch-size", "4",
               "--lanes-per-contract", "8", "--max-steps", "64",
               "--limits-profile", "test", "-t", "1",
               "-m", "AccidentallyKillable", "-o", "json",
               "--fault-inject", "raise:contract=c002",
               "--max-batch-retries", "1"])
    out = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(out)
    assert [q["name"] for q in payload["quarantined"]] == ["c002"]
    assert payload["retries"] >= 1
    assert payload["batch_status"][0] == "quarantined:1"
    assert ({i["contract"] for i in payload["issues_detail"]}
            == {"c000", "c004"})
