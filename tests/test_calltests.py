"""Independent CALL-frame vectors vs the symbolic engine.

VERDICT r2 ask #10: the frame machinery gets an oracle whose bytecode and
expectations share NO code with the engine (see
``tests/fixtures/gen_calltests.py`` — raw-byte assembler + integer
formulas). Every vector runs the same 4-lane shape so the whole suite
compiles once.
"""

import json
import os

import numpy as np
import pytest

import mythril_tpu  # noqa: F401
from mythril_tpu.config import TEST_LIMITS
from mythril_tpu.core import Corpus, make_env
from mythril_tpu.core.frontier import ACCT_CONTRACT0
from mythril_tpu.disassembler import ContractImage
from mythril_tpu.ops import u256
from mythril_tpu.symbolic import SymSpec, make_sym_frontier, sym_run

L = TEST_LIMITS
_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "calltests.json")
with open(_FIXTURE) as fh:
    _DOC = json.load(fh)
VECTORS = _DOC["tests"]
NAMES = sorted(VECTORS)

ACCT_SLOT = {"caller": ACCT_CONTRACT0, "callee": ACCT_CONTRACT0 + 1,
             "attacker": 0}


def run_vector(v):
    imgs = [ContractImage.from_bytecode(bytes.fromhex(v["caller_code"]),
                                        L.max_code),
            ContractImage.from_bytecode(bytes.fromhex(v["callee_code"]),
                                        L.max_code)]
    corpus = Corpus.from_images(imgs)
    active = np.zeros(4, dtype=bool)
    active[0] = True
    sf = make_sym_frontier(4, L, contract_id=np.zeros(4, np.int32),
                           active=active, n_contracts=2)
    env = make_env(4)
    # max_steps uniform so every vector reuses one compiled executable
    return sym_run(sf, env, corpus, SymSpec(), L, max_steps=128)


@pytest.mark.parametrize("name", NAMES)
def test_call_vector(name):
    v = VECTORS[name]
    out = run_vector(v)
    lane = 0
    assert bool(np.asarray(out.base.active)[lane])
    assert bool(np.asarray(out.base.halted)[lane]), f"{name}: lane not halted"
    assert not bool(np.asarray(out.base.error)[lane]), f"{name}: lane errored"
    assert int(np.asarray(out.base.depth)[lane]) == 0

    # exact storage comparison per account
    used = np.asarray(out.base.st_used)
    keys = np.asarray(out.base.st_keys)
    vals = np.asarray(out.base.st_vals)
    acct = np.asarray(out.base.st_acct)
    got = {}
    for k in range(used.shape[1]):
        if used[lane, k]:
            got.setdefault(int(acct[lane, k]), {})[
                u256.to_int(keys[lane, k])] = u256.to_int(vals[lane, k])
    for role, slots in v["expect_storage"].items():
        want = {int(s): int(x, 16) for s, x in slots.items()}
        assert got.get(ACCT_SLOT[role], {}) == want, (
            f"{name}: {role} storage {got.get(ACCT_SLOT[role], {})} != {want}")

    bal = np.asarray(out.base.acct_bal)
    for role, x in v["expect_balances"].items():
        assert u256.to_int(bal[lane, ACCT_SLOT[role]]) == int(x, 16), (
            f"{name}: {role} balance mismatch")
